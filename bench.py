"""Headline benchmark: training throughput on one TPU chip.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": ...}

vs_baseline is null: the reference repo is empty (SURVEY.md §0) and
publishes no numbers to compare against, so the value stands alone.

The TPU backend is probed in a subprocess with a timeout before the
main process touches it: the relay-backed TPU platform can hang (not
just raise) on init, and round 1 shipped no number because the script
died at jax.default_backend(). On probe failure we fall back to the
CPU backend and still emit the JSON line; on any other failure we emit
an error JSON line. Never a bare traceback.
"""

from __future__ import annotations

# shellac: ignore[SH015] — the shellac_bench_* gauges are bench-local
# headline series (set once per run, snapshotted into BENCH_* files),
# deliberately outside the serving bundle layer; cataloged in
# docs/observability.md §Bench.

import json
import os
import subprocess
import sys
import time

import jax


def tpu_usable(timeout_s: float = 90.0, retries: int = 1) -> bool:
    """True iff a fresh subprocess can initialize the TPU backend."""
    code = (
        "import jax\n"
        "assert jax.default_backend() == 'tpu', jax.default_backend()\n"
    )
    for attempt in range(retries + 1):
        try:
            r = subprocess.run(
                [sys.executable, "-c", code],
                timeout=timeout_s,
                capture_output=True,
            )
            # A clean exit is definitive either way (backend resolved);
            # only a hang (wedged relay) is worth retrying.
            return r.returncode == 0
        except subprocess.TimeoutExpired:
            if attempt < retries:
                time.sleep(5)
    return False


def args_nonheadline(args) -> bool:
    """True when variant flags change the recipe — cached-headline
    replay and recipe adoption only apply to the driver's plain
    `python bench.py`."""
    return bool(args.packed or args.quant or args.fused_loss
                or args.batch or args.preset)


_REPO_DIR = os.path.dirname(os.path.abspath(__file__))


def load_recipe(path=None):
    """The measured recipe scripts/adopt_recipe.py wrote, or None.
    Both sides derive the path from their own file location so any
    checkout works."""
    if path is None:
        path = os.path.join(_REPO_DIR, "bench_recipe.json")
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def latest_queue_tpu_line(path=None):
    """Newest HEADLINE-config row the watchdog queue captured this
    round (scripts/run_tpu_queue.sh appends bench.py stdout on
    success). Returns the row with provenance, or None.

    A row qualifies only when the CONFIG it measured matches the
    current headline recipe — the metric name alone is ambiguous (it
    encodes fused_loss but not batch or remat policy, so e.g. the
    --fused-loss --batch 8 variant shares a name with an adopted
    fused recipe). bench.py rows record their full config in detail;
    rows without it are trusted only for the plain name with no
    recipe in effect.
    """
    if path is None:
        path = os.path.join(_REPO_DIR, "tpu_queue_r5.jsonl")
    path = os.environ.get("SHELLAC_QUEUE_RESULTS", path)
    rec = load_recipe()
    want = {
        "batch": rec.get("batch", 6) if rec else 6,
        "remat_policy": rec.get("remat_policy", "none") if rec else "none",
        "fused_loss": rec.get("fused_loss") if rec else None,
        "quant": None,
        "packed": False,
    }
    fused = want["fused_loss"]
    name = (f"train_throughput_2048d16L_seq2048"
            f"{f'_fused{fused}' if fused else ''}_tpu")
    plain_name = "train_throughput_2048d16L_seq2048_tpu"
    best = None
    try:
        with open(path) as f:
            for line in f:
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(row.get("value"), (int, float)):
                    continue
                detail = row.get("detail") or {}
                if row.get("metric") == name and "batch" in detail:
                    if all(detail.get(k) == v for k, v in want.items()):
                        best = row  # last one wins: newest capture
                elif (row.get("metric") == plain_name
                      and "batch" not in detail and rec is None):
                    best = row  # legacy row without config detail
    except OSError:
        return None
    if best is not None:
        best = dict(best)
        best.setdefault("vs_baseline", None)
        best["note_source"] = path
    return best


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser()
    # Variant flags for perf investigation; the driver runs plain
    # `python bench.py`, which keeps the headline recipe unchanged.
    ap.add_argument("--packed", action="store_true",
                    help="packed-sequence batch (segment_ids set)")
    ap.add_argument("--quant", choices=["int8", "int8_bwd"], default=None)
    ap.add_argument("--fused-loss", type=int, default=None,
                    dest="fused_loss", metavar="CHUNK",
                    help="vocab-chunked fused cross-entropy")
    ap.add_argument("--batch", type=int, default=None)
    ap.add_argument("--preset", default=None,
                    help="model preset override (default: shellac-1b on "
                         "TPU; e.g. shellac-mla-2b for the MLA bench)")
    ap.add_argument("--no-recipe", action="store_true", dest="no_recipe",
                    help="ignore bench_recipe.json: measure the true "
                         "plain recipe (the queue uses this so the "
                         "adoption baseline stays honest every round)")
    args = ap.parse_args(argv)

    if not tpu_usable():
        # Relay down or no TPU attached. Before surrendering the
        # headline to a CPU toy number (round 3's failure mode), check
        # whether this round's watchdog queue already captured the SAME
        # bench on the real chip during a relay window — if so, replay
        # that line (clearly labeled) rather than measuring the wrong
        # hardware.
        # --no-recipe must never replay either: the replay filter keys
        # on the ADOPTED recipe's config, which is exactly what a
        # plain-baseline run is asked not to measure.
        cached = (None if args_nonheadline(args) or args.no_recipe
                  else latest_queue_tpu_line())
        if cached is not None:
            cached["note"] = (
                "relay wedged at bench time; value is this round's "
                "watchdog-captured TPU measurement (see note_source)"
            )
            print(json.dumps(cached), flush=True)
            return 0
        # Pin CPU before backend init so the main process cannot hang
        # where the probe did.
        try:
            jax.config.update("jax_platforms", "cpu")
        except RuntimeError:
            pass

    from shellac_tpu import get_model_config
    from shellac_tpu.config import TrainConfig
    from shellac_tpu.training import init_train_state, make_train_step

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    recipe = None
    if on_tpu:
        # Batch 6 is the single-chip sweet spot with bf16 adam mu and the
        # Pallas flash backward (batch 8 fits but is marginally slower).
        cfg = get_model_config(args.preset or "shellac-1b")
        batch, seq, steps = 6, 2048, 10
        if args.preset == "shellac-mla-2b":
            # 2.4B params at seq 2048: batch 4 fits comfortably.
            batch = 4
        if not args_nonheadline(args) and not args.no_recipe:
            # A measured sweep winner (scripts/adopt_recipe.py) becomes
            # the plain headline recipe — exact-math configs only, and
            # only when it beat the default by >1% on this hardware.
            recipe = load_recipe()
            if recipe is not None:
                batch = recipe.get("batch", batch)
                args.fused_loss = recipe.get("fused_loss")
                pol = recipe.get("remat_policy", "none")
                if pol and pol != "none":
                    cfg = cfg.replace(remat_policy=pol)
    else:
        cfg = get_model_config(args.preset or "tiny")
        batch, seq, steps = 4, 128, 3

    if args.batch is not None:
        batch = args.batch
    tcfg = TrainConfig(warmup_steps=10, total_steps=1000, quant=args.quant,
                       fused_loss_chunk=args.fused_loss)
    key = jax.random.PRNGKey(0)
    state = init_train_state(cfg, tcfg, key)
    step = make_train_step(cfg, tcfg)

    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq), 0, cfg.vocab_size)
    batch_data = {"inputs": tokens, "targets": tokens}
    if args.packed:
        # Four packed documents per row, boundaries off block edges —
        # the pretraining-default shape; exercises the segment-masked
        # flash kernel path.
        import numpy as _np

        bounds = [0, seq // 4 + 37, seq // 2 + 11, 3 * seq // 4 + 5, seq]
        seg = _np.zeros((batch, seq), _np.int32)
        for i in range(4):
            seg[:, bounds[i]:bounds[i + 1]] = i
        batch_data["segment_ids"] = jax.numpy.asarray(seg)

    # Warmup (compile + first step). float() forces a device-to-host
    # transfer: on the axon relay platform block_until_ready alone does
    # not actually synchronize.
    state, metrics = step(state, batch_data)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
    final_loss = float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens_per_step = batch * seq
    tok_s = tokens_per_step * steps / dt

    from shellac_tpu.models.transformer import num_params
    from shellac_tpu.utils.metrics import (
        TPU_V5E_BF16_PEAK_FLOPS,
        train_flops_per_token,
    )

    n_params = num_params(state.params)
    flops_per_token = train_flops_per_token(n_params, cfg.n_layers, cfg.d_model, seq)
    mfu_denom = TPU_V5E_BF16_PEAK_FLOPS if on_tpu else None

    variant = ("_packed" if args.packed else "") + (
        f"_{args.quant}" if args.quant else ""
    ) + (f"_fused{args.fused_loss}" if args.fused_loss else "")
    result = {
        "metric": f"train_throughput_{cfg.d_model}d{cfg.n_layers}L_seq{seq}"
                  f"{variant}_{backend}",
        "value": round(tok_s, 1),
        "unit": "tokens/s",
        "vs_baseline": None,
    }
    extra = {
        "params": n_params,
        "step_time_s": round(dt / steps, 4),
        "loss": round(final_loss, 4),
        # Full config, so consumers (adopt_recipe, the replay filter)
        # match rows on what was MEASURED, not on name parsing — the
        # metric name does not encode batch or remat policy.
        "batch": batch,
        "remat_policy": cfg.remat_policy,
        "fused_loss": args.fused_loss,
        "quant": args.quant,
        "packed": bool(args.packed),
    }
    if mfu_denom:
        extra["mfu"] = round(tok_s * flops_per_token / mfu_denom, 4)
    # Deposit the headline into the shared obs registry and snapshot it
    # into the output, so BENCH_* files carry the same series a live
    # /metrics scrape (or train --metrics-file) would — one exposition
    # path for bench, train, and serve numbers.
    from shellac_tpu.obs import get_registry

    reg = get_registry()
    reg.gauge("shellac_bench_train_tokens_per_sec",
              "Headline training-bench throughput").set(tok_s)
    reg.gauge("shellac_bench_train_step_seconds",
              "Headline training-bench mean step time").set(dt / steps)
    if mfu_denom:
        reg.gauge("shellac_bench_train_mfu",
                  "Headline training-bench MFU").set(extra["mfu"])
    extra["metrics"] = reg.snapshot()
    if recipe is not None:
        extra["recipe"] = {
            k: recipe.get(k)
            for k in ("batch", "fused_loss", "remat_policy", "source")
        }
    result["detail"] = extra
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 — the JSON line must go out
        print(
            json.dumps(
                {
                    "metric": "train_throughput",
                    "value": 0.0,
                    "unit": "tokens/s",
                    "vs_baseline": None,
                    "error": f"{type(e).__name__}: {e}",
                }
            )
        )
        sys.exit(0)
