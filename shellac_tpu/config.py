"""Configuration dataclasses for models, parallelism, and training.

Design note: the reference repo mounted at /root/reference is empty (see
SURVEY.md §0), so there is no reference config system to cite. This is an
original, TPU-first design: configs are frozen dataclasses so they can be
closed over by jitted functions as static data.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Optional

import jax.numpy as jnp

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
    "int8": jnp.int8,
}


def resolve_dtype(name):
    """Map a dtype name (or dtype) to the jnp dtype object."""
    if isinstance(name, str):
        return _DTYPES[name]
    return name


@dataclass(frozen=True)
class MoEConfig:
    """Mixture-of-experts layer configuration."""

    num_experts: int = 8
    num_experts_per_token: int = 2
    # Per-expert capacity = capacity_factor * tokens / num_experts.
    capacity_factor: float = 1.25
    router_aux_loss_weight: float = 0.01
    router_z_loss_weight: float = 1e-3
    # Never drop tokens: capacity is sized to the worst case (T per
    # expert), costing O(E*T*D) dispatch buffers. Exact Mixtral-style
    # computation — use for inference/conversion parity, not large-T
    # training.
    dropless: bool = False
    # Dropless TRAINING: sorted-segment grouped expert matmuls
    # (jax.lax.ragged_dot) — no capacity buckets, nothing drops
    # (moe_dropped_frac == 0 by construction), O(T*k*F) memory like a
    # dense MLP. The loss-sensitive fine-tuning option; decode keeps
    # the capacity-at-T path (ops/moe.py:moe_ffn_grouped).
    grouped_dropless: bool = False
    # DeepSeek-style always-active shared experts: one fused FFN of
    # hidden size num_shared_experts * expert ff width added to the
    # routed output.
    num_shared_experts: int = 0
    # Expert FFN hidden width; None = the model's ff_dim. DeepSeek MoE
    # layers use a much narrower per-expert width than dense layers
    # (moe_intermediate_size).
    d_ff_expert: Optional[int] = None
    # Renormalize the kept top-k probabilities to sum to 1. DeepSeek-V2
    # ships norm_topk_prob=False: raw softmax probabilities are used,
    # scaled by routed_scaling_factor.
    norm_topk_prob: bool = True
    routed_scaling_factor: float = 1.0
    # Group-limited routing (DeepSeek-V2/V3 big variants): experts are
    # split into n_group groups, the top `topk_group` groups stay live
    # (ranked by max member score under softmax scoring, by top-2-sum
    # under sigmoid scoring — each matching its HF reference), and
    # top-k selects within them. n_group=1 disables.
    n_group: int = 1
    topk_group: int = 1
    # "softmax" (V2), "sigmoid" (V3: sigmoid scores with an additive
    # per-expert selection bias — e_score_correction_bias — that
    # influences WHICH experts are picked, never the combine weights),
    # or "softmax_topk" (GPT-OSS: top-k over raw biased logits, softmax
    # over just the kept values).
    scoring: str = "softmax"
    # Biases on the expert projections (GPT-OSS): b_gate/b_up (E, F)
    # and b_down (E, D) ride alongside the weights.
    expert_bias: bool = False
    # GPT-OSS activation clamp: gate clamps to (-inf, limit], up to
    # [-limit, limit] before the gated product.
    gate_limit: Optional[float] = None
    # Expert FFN activation: "silu" (standard swiglu) or "gptoss"
    # ((up + 1) * gate * sigmoid(1.702 * gate), after the clamp).
    expert_act: str = "silu"


@dataclass(frozen=True)
class YarnConfig:
    """Yarn rope scaling (NTK-by-parts context extension).

    Matches the HF `rope_scaling: {"rope_type": "yarn", ...}` semantics
    exactly (transformers._compute_yarn_parameters): low frequencies
    interpolate by `factor`, high frequencies extrapolate, a linear ramp
    between the beta_fast/beta_slow rotation bounds blends them, and the
    cos/sin tables are multiplied by an attention factor (mscale).
    DeepSeek's long-context checkpoints ship with this.
    """

    factor: float
    original_max_position_embeddings: int
    beta_fast: float = 32.0
    beta_slow: float = 1.0
    mscale: Optional[float] = None
    mscale_all_dim: Optional[float] = None
    attention_factor: Optional[float] = None
    truncate: bool = True


@dataclass(frozen=True)
class Llama3RopeConfig:
    """Llama-3.1 rope scaling (wavelength-banded frequency division).

    Matches HF's `rope_scaling: {"rope_type": "llama3", ...}` exactly:
    wavelengths longer than old_context/low_freq_factor divide by
    `factor`, shorter than old_context/high_freq_factor stay put, and
    the band between interpolates smoothly. No attention factor.
    """

    factor: float
    low_freq_factor: float
    high_freq_factor: float
    original_max_position_embeddings: int


@dataclass(frozen=True)
class MLAConfig:
    """Multi-head latent attention (DeepSeek-V2/V3 style).

    K and V are generated from a shared low-rank latent: kv_a projects
    the hidden state to `kv_lora_rank` (+ a `qk_rope_head_dim` slice
    that carries position, shared by all heads, MQA-style), and kv_b
    expands the normed latent to per-head no-position keys and values.
    Queries split the same way (optionally low-rank via q_lora_rank).
    The decode cache stores ONLY the latent + roped key slice —
    `kv_lora_rank + qk_rope_head_dim` numbers per token, independent of
    the head count (see models/transformer.py for the absorbed-matrix
    decode that makes this exact).
    """

    kv_lora_rank: int = 512
    q_lora_rank: Optional[int] = None
    qk_nope_head_dim: int = 128
    qk_rope_head_dim: int = 64
    v_head_dim: int = 128

    @property
    def qk_head_dim(self) -> int:
        return self.qk_nope_head_dim + self.qk_rope_head_dim

    @property
    def cache_dim(self) -> int:
        return self.kv_lora_rank + self.qk_rope_head_dim


@dataclass(frozen=True)
class ModelConfig:
    """Decoder-only transformer configuration (LLaMA-style)."""

    vocab_size: int = 32768
    d_model: int = 512
    n_layers: int = 4
    n_heads: int = 8
    # Grouped-query attention: n_kv_heads <= n_heads, n_heads % n_kv_heads == 0.
    n_kv_heads: Optional[int] = None
    head_dim: Optional[int] = None
    d_ff: Optional[int] = None
    max_seq_len: int = 2048
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5
    # Compute dtype; parameters are kept in param_dtype (fp32 master copy).
    dtype: str = "bfloat16"
    param_dtype: str = "float32"
    tie_embeddings: bool = True
    # MLP gate activation: "swiglu" (silu) or "geglu" (tanh-gelu, Gemma).
    activation: str = "swiglu"
    # Scale token embeddings by sqrt(d_model) at input (Gemma-style).
    embed_scale: bool = False
    # Rematerialize each block in the backward pass (memory for FLOPs).
    remat: bool = True
    # What the remat may keep: "none" (recompute everything), "dots"
    # (save matmul outputs — less recompute, more HBM), "dots_no_batch".
    remat_policy: str = "none"
    # Optional sliding-window attention (None = full causal).
    attn_window: Optional[int] = None
    # Per-layer attention kinds, cycled over the depth (Gemma-2/3
    # style): entries are "window" (uses attn_window) or "full".
    # n_layers must divide into whole pattern periods. None = every
    # layer uses attn_window as-is.
    attn_pattern: Optional[tuple] = None
    # Gemma-2 tanh soft-capping of the SCALED attention scores
    # (cap * tanh(s / cap), applied before masking).
    attn_softcap: Optional[float] = None
    # Score scale override (Gemma-2's query_pre_attn_scalar**-0.5);
    # None = head_dim**-0.5.
    attn_scale: Optional[float] = None
    # Sandwich norms (Gemma-2/3): an extra RMSNorm on each residual
    # branch's OUTPUT (post-attention and post-MLP), alongside the usual
    # pre-norms.
    post_norms: bool = False
    # Learned per-head attention-sink logits (GPT-OSS): each row's
    # softmax denominator gains exp(sink_h) so attention mass can drain
    # off the real tokens. Adds a per-layer "sinks" (H,) parameter.
    attn_sink: bool = False
    # Bias on the attention OUTPUT projection (GPT-OSS puts biases on
    # o_proj too; attn_bias alone covers q/k/v).
    attn_out_bias: bool = False
    # False = bidirectional (encoder) attention. Decoder-only features
    # (KV-cache generation) require causal=True.
    causal: bool = True
    # Biases on the q/k/v projections (Qwen2-style); o_proj stays biasless.
    attn_bias: bool = False
    # If set, every `moe_every`-th layer is a MoE layer (1 = all layers).
    moe: Optional[MoEConfig] = None
    moe_every: int = 1
    # DeepSeek layout: the first k layers run dense MLPs, every later
    # layer is MoE. Mutually exclusive with moe_every > 1.
    first_k_dense: int = 0
    logit_softcap: Optional[float] = None
    # Quantized training compute: "int8" runs the dense projections as
    # int8 MXU dots (fwd only; fp32 master params untouched). Usually
    # set via TrainConfig.quant rather than directly. See ops/qtrain.py.
    quant_training: Optional[str] = None
    # Multi-head latent attention (DeepSeek-style). Replaces the
    # standard q/k/v projections; n_kv_heads must be unset (the latent
    # is shared MQA-style) and head_dim is ignored in favour of the
    # MLA dims.
    mla: Optional[MLAConfig] = None
    # Rope scaling for long-context checkpoints (applies to the
    # rope_dim — MLA's qk_rope slice or the full head_dim). At most one
    # of yarn (DeepSeek/Qwen long-context) / llama3 (Llama-3.1 family) /
    # linear (classic position interpolation; Gemma-3 global layers).
    rope_yarn: Optional[YarnConfig] = None
    rope_llama3: Optional[Llama3RopeConfig] = None
    rope_linear: Optional[float] = None
    # Gemma-3 dual rope: "window" layers of an attn_pattern rope with
    # this theta and NO scaling, while "full" layers use rope_theta plus
    # whatever scaling config is set. Requires attn_pattern.
    rope_local_theta: Optional[float] = None
    # Per-head-dim RMSNorm on q and k before rope (Qwen3-style).
    qk_norm: bool = False

    def __post_init__(self):
        # JSON configs arrive with attn_pattern as a list; the frozen
        # dataclass stores the hashable tuple every consumer expects.
        if self.attn_pattern is not None and not isinstance(
            self.attn_pattern, tuple
        ):
            object.__setattr__(self, "attn_pattern", tuple(self.attn_pattern))

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def dim_per_head(self) -> int:
        return (
            self.head_dim if self.head_dim is not None else self.d_model // self.n_heads
        )

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        # SwiGLU sizing: 2/3 * 4 * d_model, rounded up to a multiple of 128
        # so the MXU tiles cleanly (128 lanes).
        raw = int(8 * self.d_model / 3)
        return ((raw + 127) // 128) * 128

    @property
    def rope_dim(self) -> int:
        """Width of the rotary tables: MLA ropes only its qk_rope slice."""
        return (self.mla.qk_rope_head_dim if self.mla is not None
                else self.dim_per_head)

    @property
    def cache_kv_heads(self) -> int:
        """KV-cache head count: MLA caches ONE shared latent row."""
        return 1 if self.mla is not None else self.kv_heads

    @property
    def cache_head_dim(self) -> int:
        """Per-token cache width: latent + roped key slice under MLA."""
        return self.mla.cache_dim if self.mla is not None else self.dim_per_head

    @property
    def cache_v_head_dim(self) -> int:
        """V-cache width: 0 under MLA (values re-expand from the SAME
        latent the key cache stores — no second copy exists)."""
        return 0 if self.mla is not None else self.dim_per_head

    @property
    def compute_dtype(self):
        return resolve_dtype(self.dtype)

    @property
    def params_dtype(self):
        return resolve_dtype(self.param_dtype)

    def validate(self) -> "ModelConfig":
        if self.n_heads % self.kv_heads != 0:
            raise ValueError(
                f"n_heads={self.n_heads} must be divisible by n_kv_heads={self.kv_heads}"
            )
        if self.head_dim is None and self.d_model % self.n_heads != 0:
            raise ValueError(
                f"d_model={self.d_model} must be divisible by n_heads={self.n_heads}"
            )
        if self.moe is not None and self.moe_every < 1:
            raise ValueError("moe_every must be >= 1")
        if self.attn_pattern is not None:
            if not self.attn_pattern:
                raise ValueError(
                    "attn_pattern must be a non-empty tuple or None"
                )
            bad = set(self.attn_pattern) - {"window", "full"}
            if bad:
                raise ValueError(
                    f"attn_pattern entries must be 'window' or 'full'; "
                    f"got {sorted(bad)}"
                )
            if "window" in self.attn_pattern and self.attn_window is None:
                raise ValueError(
                    "attn_pattern uses 'window' layers but attn_window "
                    "is unset"
                )
            if self.n_layers % len(self.attn_pattern):
                raise ValueError(
                    f"n_layers={self.n_layers} must divide into whole "
                    f"attn_pattern periods (len {len(self.attn_pattern)})"
                )
            if self.moe_every > 1 or self.first_k_dense:
                raise ValueError(
                    "attn_pattern with interleaved dense/MoE layouts is "
                    "not supported yet (uniform layers or full MoE only)"
                )
        if self.first_k_dense:
            if self.moe is None:
                raise ValueError("first_k_dense needs a MoEConfig")
            if self.moe_every > 1:
                raise ValueError(
                    "first_k_dense and moe_every > 1 are different "
                    "layouts; pick one"
                )
            if not 0 < self.first_k_dense < self.n_layers:
                raise ValueError(
                    f"first_k_dense={self.first_k_dense} must be in "
                    f"(0, n_layers={self.n_layers})"
                )
        if self.moe is not None and self.moe.scoring not in (
            "softmax", "sigmoid", "softmax_topk",
        ):
            raise ValueError(
                f"moe.scoring={self.moe.scoring!r}; have softmax, "
                "sigmoid, softmax_topk"
            )
        if self.moe is not None and self.moe.expert_act not in (
            "silu", "gptoss",
        ):
            raise ValueError(
                f"moe.expert_act={self.moe.expert_act!r}; have silu, gptoss"
            )
        if (self.moe is not None and self.moe.scoring == "softmax_topk"
                and self.moe.n_group > 1):
            raise ValueError(
                "softmax_topk scoring has no group-limited variant"
            )
        if (self.moe is not None and self.moe.scoring == "sigmoid"
                and self.moe.n_group > 1
                and self.moe.num_experts // self.moe.n_group < 2):
            raise ValueError(
                "sigmoid scoring ranks groups by top-2 sum; groups need "
                ">= 2 experts"
            )
        if self.moe is not None and self.moe.n_group > 1:
            if self.moe.num_experts % self.moe.n_group:
                raise ValueError(
                    f"num_experts={self.moe.num_experts} must divide "
                    f"into n_group={self.moe.n_group} groups"
                )
            if not 1 <= self.moe.topk_group <= self.moe.n_group:
                raise ValueError(
                    f"topk_group={self.moe.topk_group} must be in "
                    f"[1, n_group={self.moe.n_group}]"
                )
        if self.quant_training not in (None, "int8", "int8_bwd"):
            raise ValueError(
                f"quant_training={self.quant_training!r}; "
                "have None, 'int8', 'int8_bwd'"
            )
        if sum(x is not None for x in (
            self.rope_yarn, self.rope_llama3, self.rope_linear,
        )) > 1:
            raise ValueError(
                "rope_yarn / rope_llama3 / rope_linear are exclusive"
            )
        if self.rope_local_theta is not None and (
            self.attn_pattern is None or "window" not in self.attn_pattern
        ):
            raise ValueError(
                "rope_local_theta needs an attn_pattern with 'window' "
                "layers (a uniform model just sets rope_theta)"
            )
        if self.mla is not None:
            if self.n_kv_heads is not None:
                raise ValueError(
                    "MLA shares one latent across heads (MQA-style); "
                    "leave n_kv_heads unset"
                )
            if self.attn_window is not None:
                raise ValueError("MLA with sliding windows is not defined")
            if self.attn_softcap is not None or self.attn_scale is not None:
                # The absorbed latent decode uses its own exact algebra
                # and scale; capping/rescaling would silently diverge
                # between the training forward and cached decode.
                raise ValueError(
                    "attn_softcap/attn_scale are not defined for MLA "
                    "models (the absorbed decode fixes the score scale)"
                )
            if self.attn_sink or self.attn_out_bias:
                raise ValueError(
                    "attn_sink/attn_out_bias are not defined for MLA "
                    "models"
                )
            if self.attn_bias:
                raise ValueError("MLA attn_bias is not supported yet")
            if not self.causal:
                raise ValueError("MLA is decoder-only (causal=True)")
            if self.mla.qk_rope_head_dim % 2:
                raise ValueError("qk_rope_head_dim must be even (rope pairs)")
            if self.qk_norm:
                raise ValueError("qk_norm does not apply to MLA models")
        return self

    def replace(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class ParallelConfig:
    """Sizes of the device-mesh axes.

    The mesh is laid out (dp, fsdp, pp, ep, sp, tp) from outermost
    (DCN-friendly) to innermost (ICI-friendly): tensor parallelism
    generates the most traffic per step so it rides the fastest links.

    - dp:   pure data parallelism (gradients all-reduced)
    - fsdp: data parallelism with parameter/optimizer sharding (ZeRO-3)
    - pp:   pipeline-stage axis (GPipe-style microbatched execution,
            parallel/pipeline.py)
    - ep:   expert parallelism — MoE expert weights and capacity
            buckets shard over ep; XLA inserts the token all-to-all at
            the dispatch/combine resharding boundaries (ops/moe.py)
    - sp:   sequence/context parallelism (ring attention)
    - tp:   tensor (megatron-style) parallelism within a layer
    """

    dp: int = 1
    fsdp: int = 1
    sp: int = 1
    tp: int = 1
    pp: int = 1
    ep: int = 1

    @property
    def num_devices(self) -> int:
        return self.dp * self.fsdp * self.sp * self.tp * self.pp * self.ep

    def replace(self, **kw) -> "ParallelConfig":
        return dataclasses.replace(self, **kw)


@dataclass(frozen=True)
class TrainConfig:
    """Optimizer / schedule / loop configuration."""

    learning_rate: float = 3e-4
    min_lr_ratio: float = 0.1
    warmup_steps: int = 100
    total_steps: int = 1000
    # "adamw" (default), "lion", "adafactor" (factored second moment),
    # or "muon" (Newton-Schulz-orthogonalized momentum on the stacked
    # matrices, adamw for embeddings/head/norms; b1 is its momentum).
    optimizer: str = "adamw"
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    grad_clip_norm: float = 1.0
    # Dtype for adam's first moment. bf16 halves its HBM footprint with
    # negligible quality impact (the update is still computed in fp32);
    # the second moment stays fp32 for dynamic range.
    mu_dtype: str = "bfloat16"
    # Number of microbatches accumulated per optimizer step (1 = no accum).
    grad_accum: int = 1
    z_loss_weight: float = 0.0
    # Skip the whole param/opt update when any gradient is non-finite.
    skip_nonfinite_updates: bool = True
    # Quantized training compute: None (bf16), "int8" (dense projections
    # as int8 MXU dots, fwd only), or "int8_bwd" (backward matmuls too);
    # fp32 master params either way. See ops/qtrain.py.
    quant: Optional[str] = None
    # Vocab-chunked fused cross-entropy: the (B, S, V) fp32 logits —
    # the train step's largest residual — never materialize. Set to a
    # chunk size dividing the vocab (e.g. 2048); None = unfused.
    # Ignored (with the unfused path) for models with logit_softcap.
    fused_loss_chunk: Optional[int] = None
    # Exponential moving average of parameters (e.g. 0.999): kept in
    # TrainState.ema_params, updated every step, checkpointed; eval can
    # read the averaged weights. None disables (no memory cost).
    ema_decay: Optional[float] = None
    seed: int = 0

    def replace(self, **kw) -> "TrainConfig":
        return dataclasses.replace(self, **kw)
