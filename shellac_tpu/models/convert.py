"""HuggingFace Llama-family checkpoint conversion.

Lets a user bring existing torch weights (Llama/Mistral-style decoders:
GQA + SwiGLU + RMSNorm + NeoX-form RoPE) into shellac_tpu's stacked
pytree layout:

  - torch `nn.Linear` stores (out, in); we store (in, out) → transpose.
  - HF RMSNorm weight `W` multiplies directly; ours applies `(1 + s)` →
    s = W - 1 (so a zero-init tree is the identity scale).
  - per-layer tensors stack along a leading `layers` axis to match the
    `lax.scan` forward.

Conversion is numerics-exact: the parity test compares our forward
against `transformers`' LlamaForCausalLM logits on the same weights.

Works from a live HF model, a state_dict, or a directory saved with
`save_pretrained` (loaded locally — no network).
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional

import jax.numpy as jnp
import numpy as np

from shellac_tpu.config import ModelConfig


def config_from_hf(hf_cfg) -> ModelConfig:
    """ModelConfig from a Llama/Mistral/Mixtral transformers config.

    Mistral's sliding window maps to attn_window; Mixtral's experts map
    to a dropless MoEConfig (exact top-k computation, no capacity drops)
    with every layer MoE.
    """
    from shellac_tpu.config import MoEConfig

    n_heads = hf_cfg.num_attention_heads
    head_dim = getattr(hf_cfg, "head_dim", None) or (
        hf_cfg.hidden_size // n_heads
    )
    is_gemma = getattr(hf_cfg, "model_type", "") == "gemma"
    if getattr(hf_cfg, "model_type", "") in ("deepseek_v2", "deepseek_v3"):
        return _deepseek_config(hf_cfg)
    if getattr(hf_cfg, "model_type", "") == "gemma2":
        return _gemma2_config(hf_cfg)
    if getattr(hf_cfg, "model_type", "") in ("gemma3_text", "gemma3"):
        return _gemma3_config(hf_cfg)
    if getattr(hf_cfg, "model_type", "") == "gpt_oss":
        return _gptoss_config(hf_cfg)
    moe = None
    if getattr(hf_cfg, "num_local_experts", None):
        moe = MoEConfig(
            num_experts=hf_cfg.num_local_experts,
            num_experts_per_token=hf_cfg.num_experts_per_tok,
            router_aux_loss_weight=getattr(
                hf_cfg, "router_aux_loss_coef", 0.01
            ),
            dropless=True,
        )
    if getattr(hf_cfg, "model_type", "") == "phi3":
        if getattr(hf_cfg, "partial_rotary_factor", 1.0) != 1.0:
            raise NotImplementedError(
                "phi3 partial_rotary_factor != 1 is not supported"
            )
    is_qwen3 = getattr(hf_cfg, "model_type", "") in ("qwen3", "qwen3_moe")
    if getattr(hf_cfg, "model_type", "") == "qwen3_moe":
        if getattr(hf_cfg, "mlp_only_layers", None):
            raise NotImplementedError(
                "qwen3_moe with mlp_only_layers is a mixed layout we "
                "cannot represent uniformly"
            )
        if getattr(hf_cfg, "decoder_sparse_step", 1) != 1:
            raise NotImplementedError(
                "qwen3_moe decoder_sparse_step != 1 is not representable"
            )
        moe = MoEConfig(
            num_experts=hf_cfg.num_experts,
            num_experts_per_token=hf_cfg.num_experts_per_tok,
            d_ff_expert=hf_cfg.moe_intermediate_size,
            # HF Qwen3MoeConfig defaults norm_topk_prob to False.
            norm_topk_prob=bool(getattr(hf_cfg, "norm_topk_prob", False)),
            router_aux_loss_weight=getattr(
                hf_cfg, "router_aux_loss_coef", 0.01
            ),
            dropless=True,
        )
    return ModelConfig(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=n_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", None) or n_heads,
        head_dim=head_dim,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        norm_eps=hf_cfg.rms_norm_eps,
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        attn_window=_hf_attn_window(hf_cfg),
        moe=moe,
        # Gemma: tanh-GeGLU MLP, sqrt(d)-scaled embeddings, and its
        # RMSNorm is already the (1+w) form ours uses.
        activation="geglu" if is_gemma else "swiglu",
        embed_scale=is_gemma,
        # Qwen2 puts biases on q/k/v (detected from the config flag
        # where present, else model type); Qwen3 dropped the biases in
        # favour of per-head-dim q/k RMSNorm.
        attn_bias=bool(
            getattr(hf_cfg, "attention_bias", False)
            or getattr(hf_cfg, "model_type", "") == "qwen2"
        ),
        qk_norm=is_qwen3,
        # Long-context checkpoints: yarn/llama3 convert exactly; any
        # other rope_scaling type fails loudly.
        **_rope_from_hf(
            getattr(hf_cfg, "rope_scaling", None),
            hf_cfg.max_position_embeddings,
        ),
    ).validate()


def _pattern_from_layer_types(layer_types) -> tuple:
    """Minimal-period attn_pattern from an HF layer_types list.

    HF stores one entry per layer ("sliding_attention"/"full_attention");
    our config stores the repeating period. Unknown kinds fail loudly.
    """
    kinds = []
    for t in layer_types:
        if t == "sliding_attention":
            kinds.append("window")
        elif t == "full_attention":
            kinds.append("full")
        else:
            raise NotImplementedError(f"unknown layer_type {t!r}")
    n = len(kinds)
    for p in range(1, n + 1):
        if n % p == 0 and all(kinds[i] == kinds[i % p] for i in range(n)):
            return tuple(kinds[:p])
    return tuple(kinds)


def _gemma2_config(hf_cfg) -> ModelConfig:
    """Gemma-2 config mapping: alternating local/global attention
    (layer_types -> attn_pattern), tanh soft-capping on attention scores
    and final logits, sandwich norms (post_norms), a query_pre_attn_scalar
    score scale, GeGLU, and sqrt(d)-scaled embeddings."""
    n_layers = hf_cfg.num_hidden_layers
    layer_types = getattr(hf_cfg, "layer_types", None) or [
        # Older configs predate layer_types; HF's fallback is sliding
        # attention on even layer indices.
        "sliding_attention" if i % 2 == 0 else "full_attention"
        for i in range(n_layers)
    ]
    pattern = _pattern_from_layer_types(layer_types)
    windowed = "window" in pattern
    if set(pattern) == {"window"}:
        pattern = None  # uniform window: the plain attn_window covers it
    elif set(pattern) == {"full"}:
        pattern, windowed = None, False
    qpas = getattr(hf_cfg, "query_pre_attn_scalar", None)
    return ModelConfig(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_layers=n_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", None)
        or hf_cfg.num_attention_heads,
        head_dim=getattr(hf_cfg, "head_dim", None)
        or hf_cfg.hidden_size // hf_cfg.num_attention_heads,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        norm_eps=hf_cfg.rms_norm_eps,
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", True)),
        attn_window=int(hf_cfg.sliding_window) if windowed else None,
        attn_pattern=pattern,
        attn_softcap=getattr(hf_cfg, "attn_logit_softcapping", None),
        logit_softcap=getattr(hf_cfg, "final_logit_softcapping", None),
        attn_scale=None if qpas is None else float(qpas) ** -0.5,
        post_norms=True,
        activation="geglu",
        embed_scale=True,
    ).validate()


def _gemma3_config(hf_cfg) -> ModelConfig:
    """Gemma-3 (text) config mapping: the Gemma-2 block (sandwich norms,
    GeGLU, scaled embeddings, patterned local/global attention) minus
    the softcaps, plus Qwen3-style per-head-dim q/k RMSNorm and DUAL
    rope — local layers rope with rope_local_base_freq unscaled, global
    layers with rope_theta and the checkpoint's (linear) rope scaling.
    """
    if getattr(hf_cfg, "model_type", "") == "gemma3":
        # Multimodal wrapper config: the text tower's config nests under
        # text_config; vision conversion is out of scope.
        inner = getattr(hf_cfg, "text_config", None)
        if inner is None:
            raise NotImplementedError(
                "gemma3 config without a text_config (vision-only?)"
            )
        hf_cfg = inner
    n_layers = hf_cfg.num_hidden_layers
    swp = getattr(hf_cfg, "sliding_window_pattern", None) or 6
    layer_types = getattr(hf_cfg, "layer_types", None) or [
        # Older configs predate layer_types: every swp-th layer is
        # global (sliding_window_pattern, default 6).
        "full_attention" if (i + 1) % swp == 0 else "sliding_attention"
        for i in range(n_layers)
    ]
    pattern = _pattern_from_layer_types(layer_types)
    windowed = "window" in pattern
    uniform = len(set(pattern)) == 1
    if uniform:
        pattern = None
    rope_kw = _rope_from_hf(
        getattr(hf_cfg, "rope_scaling", None),
        hf_cfg.max_position_embeddings,
    )
    rope_linear = rope_kw.pop("rope_linear", None)
    if rope_kw:
        raise NotImplementedError(
            f"gemma3 with {sorted(rope_kw)} rope scaling (have: linear)"
        )
    qpas = getattr(hf_cfg, "query_pre_attn_scalar", None)
    local_theta = getattr(hf_cfg, "rope_local_base_freq", None)
    rope_theta = getattr(hf_cfg, "rope_theta", 1000000.0)
    if uniform and windowed and local_theta is not None:
        # Every layer is sliding: the local frequency base IS the rope,
        # and the global-layer scaling never applies.
        rope_theta, rope_linear = float(local_theta), None
    return ModelConfig(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_layers=n_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", None)
        or hf_cfg.num_attention_heads,
        head_dim=getattr(hf_cfg, "head_dim", None)
        or hf_cfg.hidden_size // hf_cfg.num_attention_heads,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=rope_theta,
        rope_linear=rope_linear,
        rope_local_theta=(float(local_theta)
                          if windowed and not uniform
                          and local_theta is not None else None),
        norm_eps=hf_cfg.rms_norm_eps,
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", True)),
        attn_window=int(hf_cfg.sliding_window) if windowed else None,
        attn_pattern=pattern,
        attn_scale=None if qpas is None else float(qpas) ** -0.5,
        qk_norm=True,
        post_norms=True,
        activation="geglu",
        embed_scale=True,
    ).validate()


def _gptoss_config(hf_cfg) -> ModelConfig:
    """GPT-OSS config mapping: alternating sliding/full attention with
    learned per-head SINK logits, q/k/v/o biases, yarn rope (truncate
    False), and an all-MoE stack with the softmax-after-top-k gate,
    biased experts, the clamped (up+1)*glu activation, and narrow
    per-expert FFNs."""
    from shellac_tpu.config import MoEConfig

    n_layers = hf_cfg.num_hidden_layers
    layer_types = getattr(hf_cfg, "layer_types", None) or [
        "sliding_attention" if i % 2 == 0 else "full_attention"
        for i in range(n_layers)
    ]
    pattern = _pattern_from_layer_types(layer_types)
    windowed = "window" in pattern
    if set(pattern) == {"window"}:
        pattern = None
    elif set(pattern) == {"full"}:
        pattern, windowed = None, False
    moe = MoEConfig(
        num_experts=hf_cfg.num_local_experts,
        num_experts_per_token=hf_cfg.num_experts_per_tok,
        d_ff_expert=hf_cfg.intermediate_size,
        scoring="softmax_topk",
        expert_bias=True,
        # HF hardcodes these in GptOssExperts (no config fields).
        gate_limit=7.0,
        expert_act="gptoss",
        router_aux_loss_weight=getattr(hf_cfg, "router_aux_loss_coef",
                                       0.9),
        dropless=True,
    )
    return ModelConfig(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_layers=n_layers,
        n_heads=hf_cfg.num_attention_heads,
        n_kv_heads=getattr(hf_cfg, "num_key_value_heads", None)
        or hf_cfg.num_attention_heads,
        head_dim=getattr(hf_cfg, "head_dim", None)
        or hf_cfg.hidden_size // hf_cfg.num_attention_heads,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 150000.0),
        norm_eps=hf_cfg.rms_norm_eps,
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        attn_window=int(hf_cfg.sliding_window) if windowed else None,
        attn_pattern=pattern,
        attn_bias=bool(getattr(hf_cfg, "attention_bias", True)),
        attn_out_bias=bool(getattr(hf_cfg, "attention_bias", True)),
        attn_sink=True,
        moe=moe,
        **_rope_from_hf(
            getattr(hf_cfg, "rope_scaling", None),
            hf_cfg.max_position_embeddings,
        ),
    ).validate()


def _deepseek_config(hf_cfg) -> ModelConfig:
    """DeepSeek-V2/V3 (MLA) config mapping.

    Supports the full architecture: MLA attention (with optional yarn
    rope), the first-k-dense layer layout, and both MoE gates — V2's
    softmax scoring with greedy or group-limited top-k and
    un-normalized scaled probabilities, and V3's sigmoid scoring with
    selection-only correction biases, top-2-sum group ranking, and
    normalized weights — plus narrow per-expert FFNs
    (moe_intermediate_size) and shared experts. Unrepresentable knobs
    (per-layer MoE frequency, non-yarn rope scaling, attention biases,
    gating declared different from each HF reference) fail loudly
    rather than converting approximately.
    """
    from shellac_tpu.config import MLAConfig, MoEConfig

    n_layers = hf_cfg.num_hidden_layers
    first_k = getattr(hf_cfg, "first_k_dense_replace", n_layers)
    is_v3 = getattr(hf_cfg, "model_type", "") == "deepseek_v3"
    moe = None
    if first_k < n_layers and getattr(hf_cfg, "n_routed_experts", None):
        if getattr(hf_cfg, "moe_layer_freq", 1) != 1:
            raise NotImplementedError(
                "moe_layer_freq != 1 is not representable by the "
                "first_k_dense layout"
            )
        if first_k == 0:
            raise NotImplementedError(
                "all-MoE DeepSeek (first_k_dense_replace=0) conversion "
                "is not wired; every published checkpoint keeps >= 1 "
                "dense layer"
            )
        common = dict(
            num_experts=hf_cfg.n_routed_experts,
            num_experts_per_token=hf_cfg.num_experts_per_tok,
            d_ff_expert=hf_cfg.moe_intermediate_size,
            num_shared_experts=getattr(hf_cfg, "n_shared_experts", 0) or 0,
            routed_scaling_factor=float(
                getattr(hf_cfg, "routed_scaling_factor", 1.0)
            ),
            dropless=True,
        )
        if is_v3:
            # V3 gate: sigmoid scores, bias-corrected top-2-sum group
            # selection, normalized combine weights. If the checkpoint's
            # config DECLARES different gating (remote-code variants
            # carry these fields), refuse rather than convert wrong.
            declared = getattr(hf_cfg, "scoring_func", "sigmoid")
            if declared != "sigmoid":
                raise NotImplementedError(
                    f"deepseek_v3 with scoring_func={declared!r} "
                    "(the HF reference gate is sigmoid)"
                )
            declared_tm = getattr(hf_cfg, "topk_method", "noaux_tc")
            if declared_tm != "noaux_tc":
                raise NotImplementedError(
                    f"deepseek_v3 with topk_method={declared_tm!r} "
                    "(the HF reference gate is noaux_tc)"
                )
            moe = MoEConfig(
                scoring="sigmoid",
                norm_topk_prob=bool(getattr(hf_cfg, "norm_topk_prob", True)),
                n_group=getattr(hf_cfg, "n_group", 1) or 1,
                topk_group=getattr(hf_cfg, "topk_group", 1) or 1,
                **common,
            )
        else:
            if getattr(hf_cfg, "scoring_func", "softmax") != "softmax":
                raise NotImplementedError(
                    f"DeepSeek-V2 scoring_func="
                    f"{hf_cfg.scoring_func!r} (have: softmax)"
                )
            if getattr(hf_cfg, "topk_method", "greedy") not in (
                "greedy", "group_limited_greedy",
            ):
                raise NotImplementedError(
                    f"DeepSeek topk_method={hf_cfg.topk_method!r}"
                )
            grouped = hf_cfg.topk_method == "group_limited_greedy"
            moe = MoEConfig(
                # HF's DeepseekV2 gate NEVER renormalizes the kept top-k
                # probabilities (the config flag is unused in its
                # forward), so matching HF's actual compute means False
                # regardless of what the checkpoint's config claims.
                norm_topk_prob=False,
                n_group=(getattr(hf_cfg, "n_group", 1) or 1)
                if grouped else 1,
                topk_group=(getattr(hf_cfg, "topk_group", 1) or 1)
                if grouped else 1,
                **common,
            )
    elif first_k < n_layers:
        raise NotImplementedError(
            "first_k_dense_replace set but n_routed_experts missing"
        )
    if getattr(hf_cfg, "attention_bias", False):
        raise NotImplementedError(
            "DeepSeek attention_bias=True is not supported; converting "
            "would silently drop the bias tensors"
        )
    return ModelConfig(
        vocab_size=hf_cfg.vocab_size,
        d_model=hf_cfg.hidden_size,
        n_layers=hf_cfg.num_hidden_layers,
        n_heads=hf_cfg.num_attention_heads,
        d_ff=hf_cfg.intermediate_size,
        max_seq_len=hf_cfg.max_position_embeddings,
        rope_theta=getattr(hf_cfg, "rope_theta", 10000.0),
        norm_eps=hf_cfg.rms_norm_eps,
        tie_embeddings=bool(getattr(hf_cfg, "tie_word_embeddings", False)),
        mla=MLAConfig(
            kv_lora_rank=hf_cfg.kv_lora_rank,
            q_lora_rank=getattr(hf_cfg, "q_lora_rank", None),
            qk_nope_head_dim=hf_cfg.qk_nope_head_dim,
            qk_rope_head_dim=hf_cfg.qk_rope_head_dim,
            v_head_dim=hf_cfg.v_head_dim,
        ),
        moe=moe,
        first_k_dense=first_k if moe is not None else 0,
        **_rope_from_hf(
            getattr(hf_cfg, "rope_scaling", None),
            hf_cfg.max_position_embeddings,
        ),
    ).validate()


def _rope_from_hf(rs, max_pos) -> dict:
    """ModelConfig rope-scaling kwargs from an HF rope_scaling dict.

    yarn (DeepSeek/Qwen long-context) and llama3 (Llama-3.1 family)
    convert exactly; other scaling types fail loudly.
    """
    if not rs:
        return {}
    from shellac_tpu.config import Llama3RopeConfig, YarnConfig

    kind = rs.get("rope_type", rs.get("type"))
    if kind in ("linear", "default"):
        # Classic position interpolation: every inverse frequency
        # divides by the factor ("default" means no change).
        if kind == "default" or float(rs.get("factor", 1.0)) == 1.0:
            return {}
        return {"rope_linear": float(rs["factor"])}
    if kind == "llama3":
        if not rs.get("original_max_position_embeddings"):
            # Required: falling back to the post-scaling max would shift
            # both wavelength bands by the factor — silent divergence.
            raise ValueError(
                "llama3 rope_scaling requires "
                "original_max_position_embeddings"
            )
        return {"rope_llama3": Llama3RopeConfig(
            factor=rs["factor"],
            low_freq_factor=rs["low_freq_factor"],
            high_freq_factor=rs["high_freq_factor"],
            original_max_position_embeddings=rs[
                "original_max_position_embeddings"
            ],
        )}
    if kind != "yarn":
        raise NotImplementedError(
            f"rope_scaling type {kind!r} is not supported "
            "(have: linear, yarn, llama3)"
        )
    return {"rope_yarn": YarnConfig(
        factor=rs["factor"],
        original_max_position_embeddings=rs.get(
            "original_max_position_embeddings"
        ) or max_pos,
        beta_fast=rs.get("beta_fast") or 32.0,
        beta_slow=rs.get("beta_slow") or 1.0,
        mscale=rs.get("mscale"),
        mscale_all_dim=rs.get("mscale_all_dim"),
        attention_factor=rs.get("attention_factor"),
        truncate=rs.get("truncate", True),
    )}


def _hf_attn_window(hf_cfg) -> Optional[int]:
    """Sliding-window size, honoring the flags HF actually checks.

    Qwen2 configs routinely ship sliding_window set but
    use_sliding_window=False — HF ignores the window there, so we must
    too. Per-layer windowing (max_window_layers < n_layers with SWA
    enabled) has no uniform-window equivalent; refuse rather than
    silently diverge.
    """
    window = getattr(hf_cfg, "sliding_window", None)
    if window is None or not getattr(hf_cfg, "use_sliding_window", True):
        return None
    # HF semantics: the first max_window_layers layers run FULL
    # attention; only layers beyond them use SWA. So mwl >= n_layers
    # means no layer is windowed, mwl == 0 means all are, and anything
    # in between is per-layer mixing we cannot represent uniformly.
    mwl = getattr(hf_cfg, "max_window_layers", None)
    if mwl is None or mwl == 0:
        return int(window)
    if mwl >= hf_cfg.num_hidden_layers:
        return None
    raise ValueError(
        f"per-layer sliding window (first max_window_layers={mwl} of "
        f"n_layers={hf_cfg.num_hidden_layers} full, rest windowed) is "
        "not representable as a uniform attn_window; refusing to convert"
    )


def _norm_offset(hf_cfg) -> float:
    """What to add to HF norm weights to get our (1+s) convention.

    Llama/Mistral/Mixtral RMSNorm multiplies by w directly -> s = w - 1.
    The Gemma family stores (1 + w) semantics natively -> s = w.
    """
    gemma_family = ("gemma", "gemma2", "gemma3", "gemma3_text")
    return (0.0 if getattr(hf_cfg, "model_type", "") in gemma_family
            else -1.0)


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        return t.detach().to("cpu").float().numpy()
    return np.asarray(t, np.float32)


_ATTN_MAP = {
    # ours: (hf suffix, transpose?)
    "wq": ("self_attn.q_proj.weight", True),
    "wk": ("self_attn.k_proj.weight", True),
    "wv": ("self_attn.v_proj.weight", True),
    "wo": ("self_attn.o_proj.weight", True),
}

_DENSE_MLP_MAP = {
    "w_gate": ("mlp.gate_proj.weight", True),
    "w_up": ("mlp.up_proj.weight", True),
    "w_down": ("mlp.down_proj.weight", True),
}

# Mixtral experts: w1 = gate, w3 = up, w2 = down.
_EXPERT_MAP = {
    "w_gate": "w1",
    "w_up": "w3",
    "w_down": "w2",
}

# Qwen3-MoE (and DeepSeek) experts keep the dense projection names.
_QWEN3_EXPERT_MAP = {
    "w_gate": "gate_proj",
    "w_up": "up_proj",
    "w_down": "down_proj",
}

# Qwen2-style attention biases (vectors, no transpose).
_BIAS_MAP = {
    "bq": "self_attn.q_proj.bias",
    "bk": "self_attn.k_proj.bias",
    "bv": "self_attn.v_proj.bias",
}


def _collect_mla_layer(layers, m, get, base, norm_offset) -> None:
    """One DeepSeek (MLA) layer's attention weights into the stacks.

    kv_b_proj is one (H*(nope+v), kv_rank) matrix in HF; we split it
    into the key expansion `wkv_b_k` (kv_rank, H, nope) and value
    expansion `wkv_b_v` (kv_rank, H, v) that the absorbed decode
    contracts separately (models/transformer._mla_attention).
    """
    a = base + "self_attn."
    layers["wkv_a"].append(get(a + "kv_a_proj_with_mqa.weight").T)
    layers["kv_a_norm"].append(
        get(a + "kv_a_layernorm.weight") + norm_offset
    )
    kv_b = get(a + "kv_b_proj.weight").T  # (kv_rank, H*(nope+v))
    kv_b = kv_b.reshape(
        m.kv_lora_rank, -1, m.qk_nope_head_dim + m.v_head_dim
    )
    layers["wkv_b_k"].append(kv_b[..., : m.qk_nope_head_dim])
    layers["wkv_b_v"].append(kv_b[..., m.qk_nope_head_dim:])
    layers["wo"].append(get(a + "o_proj.weight").T)
    if m.q_lora_rank is None:
        layers["wq"].append(get(a + "q_proj.weight").T)
    else:
        layers["wq_a"].append(get(a + "q_a_proj.weight").T)
        layers["q_a_norm"].append(
            get(a + "q_a_layernorm.weight") + norm_offset
        )
        layers["wq_b"].append(get(a + "q_b_proj.weight").T)


def params_from_state_dict(
    state_dict: Mapping[str, Any], cfg: ModelConfig, dtype=None,
    norm_offset: float = -1.0, moe_naming: str = "auto",
) -> Dict[str, Any]:
    """Convert an HF Llama-family state_dict to a shellac_tpu pytree.

    norm_offset is added to HF norm weights (-1.0 for Llama-convention
    RMSNorm, 0.0 for Gemma; see _norm_offset).
    """
    sd = dict(state_dict)
    # Accept both bare and "model."-prefixed keys.
    prefix = "model." if any(k.startswith("model.") for k in sd) else ""
    pdt = dtype or cfg.params_dtype

    def get(name):
        key = f"{prefix}{name}"
        if key not in sd:
            raise KeyError(
                f"missing weight {key!r}; is this a Llama-family checkpoint?"
            )
        return _to_np(sd[key])

    if cfg.first_k_dense:
        return _first_k_params(cfg, get, sd, pdt, norm_offset)
    moe = cfg.moe is not None
    if moe and moe_naming == "auto":
        # Probe the keys: Mixtral ships block_sparse_moe.*, Qwen3-MoE
        # keeps the dense projection names under mlp.experts.*, GPT-OSS
        # fuses all experts into single stacked tensors.
        if f"{prefix}layers.0.mlp.experts.gate_up_proj" in sd:
            moe_naming = "gpt_oss"
        elif f"{prefix}layers.0.mlp.experts.0.gate_proj.weight" in sd:
            moe_naming = "qwen3_moe"
        else:
            moe_naming = "mixtral"
    if moe and cfg.moe_every > 1:
        raise NotImplementedError(
            "interleaved dense/MoE stacks (moe_every > 1) have no HF "
            "(Mixtral) checkpoint layout to convert from"
        )
    mlp_keys = (["w_router"] + list(_EXPERT_MAP) if moe
                else list(_DENSE_MLP_MAP))
    if moe and cfg.moe.scoring in ("sigmoid", "softmax_topk"):
        mlp_keys += ["b_router"]
    if moe and cfg.moe.expert_bias:
        mlp_keys += ["b_gate", "b_up", "b_down"]
    bias_keys = list(_BIAS_MAP) if cfg.attn_bias else []
    if cfg.attn_out_bias:
        bias_keys += ["bo"]
    if cfg.attn_sink:
        bias_keys += ["sinks"]
    if cfg.mla is not None:
        attn_keys = ["wkv_a", "kv_a_norm", "wkv_b_k", "wkv_b_v", "wo"]
        attn_keys += (["wq"] if cfg.mla.q_lora_rank is None
                      else ["wq_a", "q_a_norm", "wq_b"])
    else:
        attn_keys = list(_ATTN_MAP)
        if cfg.qk_norm:
            attn_keys += ["q_norm", "k_norm"]
    norm_keys = ["attn_norm", "mlp_norm"]
    if cfg.post_norms:
        norm_keys += ["post_attn_norm", "post_mlp_norm"]
    layers: Dict[str, list] = {
        k: []
        for k in [*attn_keys, *bias_keys, *mlp_keys, *norm_keys]
    }
    # Phi3 fuses q/k/v into one qkv_proj and gate/up into gate_up_proj;
    # detect from the keys and split on conversion.
    fused_qkv = f"{prefix}layers.0.self_attn.qkv_proj.weight" in sd
    for i in range(cfg.n_layers):
        base = f"layers.{i}."
        if cfg.mla is not None:
            _collect_mla_layer(layers, cfg.mla, get, base, norm_offset)
        elif fused_qkv:
            w = get(base + "self_attn.qkv_proj.weight").T  # (d, q+2kv)
            qd = cfg.n_heads * cfg.dim_per_head
            kvd = cfg.kv_heads * cfg.dim_per_head
            layers["wq"].append(w[:, :qd])
            layers["wk"].append(w[:, qd:qd + kvd])
            layers["wv"].append(w[:, qd + kvd:])
            layers["wo"].append(get(base + "self_attn.o_proj.weight").T)
        else:
            for ours, (theirs, transpose) in _ATTN_MAP.items():
                w = get(base + theirs)
                layers[ours].append(w.T if transpose else w)
            if cfg.qk_norm:
                layers["q_norm"].append(
                    get(base + "self_attn.q_norm.weight") + norm_offset
                )
                layers["k_norm"].append(
                    get(base + "self_attn.k_norm.weight") + norm_offset
                )
        for ours, theirs in (_BIAS_MAP.items() if cfg.attn_bias else ()):
            layers[ours].append(get(base + theirs))
        if cfg.attn_out_bias:
            layers["bo"].append(get(base + "self_attn.o_proj.bias"))
        if cfg.attn_sink:
            layers["sinks"].append(get(base + "self_attn.sinks"))
        if moe:
            if moe_naming == "gpt_oss":
                layers["w_router"].append(
                    get(base + "mlp.router.weight").T
                )
                layers["b_router"].append(get(base + "mlp.router.bias"))
                # Fused stacked experts: gate_up (E, D, 2F) INTERLEAVES
                # gate and up on the last dim; down is (E, F, D).
                gu = get(base + "mlp.experts.gate_up_proj")
                gub = get(base + "mlp.experts.gate_up_proj_bias")
                layers["w_gate"].append(gu[..., 0::2])
                layers["w_up"].append(gu[..., 1::2])
                layers["b_gate"].append(gub[..., 0::2])
                layers["b_up"].append(gub[..., 1::2])
                layers["w_down"].append(get(base + "mlp.experts.down_proj"))
                layers["b_down"].append(
                    get(base + "mlp.experts.down_proj_bias")
                )
            elif moe_naming == "qwen3_moe":
                layers["w_router"].append(get(base + "mlp.gate.weight").T)
                for ours, proj in _QWEN3_EXPERT_MAP.items():
                    layers[ours].append(np.stack([
                        get(base + f"mlp.experts.{j}.{proj}.weight").T
                        for j in range(cfg.moe.num_experts)
                    ]))
            else:
                layers["w_router"].append(
                    get(base + "block_sparse_moe.gate.weight").T
                )
                for ours, theirs in _EXPERT_MAP.items():
                    experts = [
                        get(
                            base
                            + f"block_sparse_moe.experts.{j}.{theirs}.weight"
                        ).T
                        for j in range(cfg.moe.num_experts)
                    ]
                    layers[ours].append(np.stack(experts))
        elif fused_qkv:
            gu = get(base + "mlp.gate_up_proj.weight").T  # (d, 2f)
            f = gu.shape[1] // 2
            layers["w_gate"].append(gu[:, :f])
            layers["w_up"].append(gu[:, f:])
            layers["w_down"].append(get(base + "mlp.down_proj.weight").T)
        else:
            for ours, (theirs, transpose) in _DENSE_MLP_MAP.items():
                w = get(base + theirs)
                layers[ours].append(w.T if transpose else w)
        layers["attn_norm"].append(
            get(base + "input_layernorm.weight") + norm_offset
        )
        if cfg.post_norms:
            # Gemma-2 sandwich norms: HF's post_attention_layernorm is
            # the attention OUTPUT norm (our post_attn_norm); the MLP
            # pre-norm is pre_feedforward_layernorm.
            layers["post_attn_norm"].append(
                get(base + "post_attention_layernorm.weight") + norm_offset
            )
            layers["mlp_norm"].append(
                get(base + "pre_feedforward_layernorm.weight") + norm_offset
            )
            layers["post_mlp_norm"].append(
                get(base + "post_feedforward_layernorm.weight") + norm_offset
            )
        else:
            layers["mlp_norm"].append(
                get(base + "post_attention_layernorm.weight") + norm_offset
            )

    params: Dict[str, Any] = {
        "embed": jnp.asarray(get("embed_tokens.weight"), pdt),
        "layers": {
            k: jnp.asarray(np.stack(v), pdt) for k, v in layers.items()
        },
        "final_norm": jnp.asarray(get("norm.weight") + norm_offset, pdt),
    }
    if not cfg.tie_embeddings:
        lm_head = sd.get("lm_head.weight")
        if lm_head is None:
            raise KeyError("untied config but no lm_head.weight in state_dict")
        params["lm_head"] = jnp.asarray(_to_np(lm_head).T, pdt)
    return params


def _first_k_params(cfg, get, sd, pdt, norm_offset):
    """DeepSeek first-k-dense checkpoint -> two-stack layer tree.

    Dense prefix layers carry plain MLPs (mlp.gate_proj...); MoE layers
    carry the router (mlp.gate.weight, stored (E, D) in HF), narrow
    per-expert FFNs (mlp.experts.{j}...), and optional shared experts
    (mlp.shared_experts...). Attention is MLA on every layer.
    """
    m = cfg.mla
    if m is None:
        raise NotImplementedError(
            "first_k_dense conversion is wired for MLA (DeepSeek) "
            "checkpoints only"
        )

    def collect(layer_range, moe_layer):
        from collections import defaultdict

        stacks: Dict[str, list] = defaultdict(list)
        put = lambda key, val: stacks[key].append(val)  # noqa: E731

        for i in layer_range:
            base = f"layers.{i}."
            _collect_mla_layer(stacks, m, get, base, norm_offset)
            put("attn_norm",
                get(base + "input_layernorm.weight") + norm_offset)
            put("mlp_norm",
                get(base + "post_attention_layernorm.weight") + norm_offset)
            if not moe_layer:
                for ours, (theirs, _) in _DENSE_MLP_MAP.items():
                    put(ours, get(base + theirs).T)
            else:
                put("w_router", get(base + "mlp.gate.weight").T)  # (D, E)
                if cfg.moe.scoring == "sigmoid":
                    put("b_router",
                        get(base + "mlp.gate.e_score_correction_bias"))
                for ours, proj in _QWEN3_EXPERT_MAP.items():
                    put(ours, np.stack([
                        get(base + f"mlp.experts.{j}.{proj}.weight").T
                        for j in range(cfg.moe.num_experts)
                    ]))
                if cfg.moe.num_shared_experts > 0:
                    for ours, proj in (
                        ("w_gate_shared", "gate_proj"),
                        ("w_up_shared", "up_proj"),
                        ("w_down_shared", "down_proj"),
                    ):
                        put(ours, get(
                            base + f"mlp.shared_experts.{proj}.weight"
                        ).T)
        return {k: jnp.asarray(np.stack(v), pdt) for k, v in stacks.items()}

    kk = cfg.first_k_dense
    params: Dict[str, Any] = {
        "embed": jnp.asarray(get("embed_tokens.weight"), pdt),
        "layers": {
            "dense": collect(range(kk), False),
            "moe": collect(range(kk, cfg.n_layers), True),
        },
        "final_norm": jnp.asarray(get("norm.weight") + norm_offset, pdt),
    }
    if not cfg.tie_embeddings:
        lm_head = sd.get("lm_head.weight")
        if lm_head is None:
            raise KeyError("untied config but no lm_head.weight in state_dict")
        params["lm_head"] = jnp.asarray(_to_np(lm_head).T, pdt)
    return params


def to_state_dict(cfg: ModelConfig, params) -> Dict[str, np.ndarray]:
    """Inverse of params_from_state_dict (Llama/Mistral/Mixtral-style).

    Returns HF-named numpy arrays ("model."-prefixed), so trained or
    LoRA-merged weights can go back into the torch/transformers world
    (build a Llama/Mixtral ForCausalLM and `load_state_dict`). MoE
    models export to the Mixtral naming (block_sparse_moe); shared
    experts have no HF counterpart and are refused.
    """
    moe = cfg.moe is not None
    if cfg.mla is not None and moe:
        raise NotImplementedError(
            "MLA + MoE export would mix DeepSeek attention names with "
            "Mixtral MLP names — no HF architecture loads that; "
            "dense-MLP MLA models export fine"
        )
    if moe and cfg.moe.num_shared_experts > 0:
        raise NotImplementedError(
            "shared experts have no HF (Mixtral) state_dict equivalent"
        )
    if moe and cfg.moe_every > 1:
        raise NotImplementedError(
            "interleaved dense/MoE stacks (moe_every > 1) have no HF "
            "(Mixtral) state_dict equivalent"
        )
    if cfg.first_k_dense:
        raise NotImplementedError(
            "first_k_dense export is not wired yet (two-stack tree); "
            "import direction is supported"
        )

    def np_(x):
        return np.asarray(x, np.float32)

    # Export norm offset mirrors the import side's _norm_offset: the
    # Gemma family (detected the same way the import config mapping
    # sets it up: GeGLU + scaled embeddings) stores (1 + w) natively,
    # so our internal s exports unchanged; Llama-convention targets
    # store w directly, so s exports as s + 1.
    gemma_family = cfg.activation == "geglu" and cfg.embed_scale
    noff = 0.0 if gemma_family else 1.0
    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np_(params["embed"]),
        "model.norm.weight": np_(params["final_norm"]) + noff,
    }
    layers = params["layers"]
    for i in range(cfg.n_layers):
        base = f"model.layers.{i}."
        if cfg.mla is not None:
            # Re-fuse the split expansions into HF's single kv_b_proj:
            # (kv_rank, H, nope) ++ (kv_rank, H, v) -> (H*(nope+v), rank).
            m = cfg.mla
            a = base + "self_attn."
            sd[a + "kv_a_proj_with_mqa.weight"] = np_(layers["wkv_a"][i]).T
            sd[a + "kv_a_layernorm.weight"] = (
                np_(layers["kv_a_norm"][i]) + 1.0
            )
            kv_b = np.concatenate(
                [np_(layers["wkv_b_k"][i]), np_(layers["wkv_b_v"][i])],
                axis=-1,
            )  # (kv_rank, H, nope + v)
            sd[a + "kv_b_proj.weight"] = kv_b.reshape(
                m.kv_lora_rank, -1
            ).T
            sd[a + "o_proj.weight"] = np_(layers["wo"][i]).T
            if m.q_lora_rank is None:
                sd[a + "q_proj.weight"] = np_(layers["wq"][i]).T
            else:
                sd[a + "q_a_proj.weight"] = np_(layers["wq_a"][i]).T
                sd[a + "q_a_layernorm.weight"] = (
                    np_(layers["q_a_norm"][i]) + 1.0
                )
                sd[a + "q_b_proj.weight"] = np_(layers["wq_b"][i]).T
        else:
            for ours, (theirs, transpose) in _ATTN_MAP.items():
                w = np_(layers[ours][i])
                sd[base + theirs] = w.T if transpose else w
            if cfg.qk_norm:
                sd[base + "self_attn.q_norm.weight"] = (
                    np_(layers["q_norm"][i]) + noff
                )
                sd[base + "self_attn.k_norm.weight"] = (
                    np_(layers["k_norm"][i]) + noff
                )
        if cfg.attn_bias:
            for ours, theirs in _BIAS_MAP.items():
                sd[base + theirs] = np_(layers[ours][i])
        if cfg.attn_out_bias:
            sd[base + "self_attn.o_proj.bias"] = np_(layers["bo"][i])
        if cfg.attn_sink:
            sd[base + "self_attn.sinks"] = np_(layers["sinks"][i])
        if moe and (cfg.moe.scoring == "softmax_topk"
                    or cfg.moe.expert_bias):
            if not (cfg.moe.scoring == "softmax_topk"
                    and cfg.moe.expert_bias):
                raise NotImplementedError(
                    "softmax_topk scoring and expert_bias only export "
                    "TOGETHER (the GPT-OSS layout); no HF architecture "
                    "matches the partial combination"
                )
            # GPT-OSS fused-expert export: re-interleave gate/up.
            sd[base + "mlp.router.weight"] = np_(layers["w_router"][i]).T
            sd[base + "mlp.router.bias"] = np_(layers["b_router"][i])
            wg = np_(layers["w_gate"][i])  # (E, D, F)
            wu = np_(layers["w_up"][i])
            gu = np.empty((*wg.shape[:-1], 2 * wg.shape[-1]), np.float32)
            gu[..., 0::2], gu[..., 1::2] = wg, wu
            sd[base + "mlp.experts.gate_up_proj"] = gu
            bg = np_(layers["b_gate"][i])
            bu = np_(layers["b_up"][i])
            gub = np.empty((*bg.shape[:-1], 2 * bg.shape[-1]), np.float32)
            gub[..., 0::2], gub[..., 1::2] = bg, bu
            sd[base + "mlp.experts.gate_up_proj_bias"] = gub
            sd[base + "mlp.experts.down_proj"] = np_(layers["w_down"][i])
            sd[base + "mlp.experts.down_proj_bias"] = np_(
                layers["b_down"][i]
            )
        elif moe and cfg.qk_norm:
            # qk_norm + MoE is the Qwen3-MoE shape: export its naming.
            sd[base + "mlp.gate.weight"] = np_(layers["w_router"][i]).T
            for ours, proj in _QWEN3_EXPERT_MAP.items():
                stacked = np_(layers[ours][i])
                for j in range(cfg.moe.num_experts):
                    sd[base + f"mlp.experts.{j}.{proj}.weight"] = (
                        stacked[j].T
                    )
        elif moe:
            sd[base + "block_sparse_moe.gate.weight"] = np_(
                layers["w_router"][i]
            ).T
            for ours, theirs in _EXPERT_MAP.items():
                stacked = np_(layers[ours][i])  # (E, in, out)
                for j in range(cfg.moe.num_experts):
                    sd[
                        base + f"block_sparse_moe.experts.{j}.{theirs}.weight"
                    ] = stacked[j].T
        else:
            for ours, (theirs, transpose) in _DENSE_MLP_MAP.items():
                w = np_(layers[ours][i])
                sd[base + theirs] = w.T if transpose else w
        sd[base + "input_layernorm.weight"] = (
            np_(layers["attn_norm"][i]) + noff
        )
        if cfg.post_norms:
            # Gemma-2 sandwich-norm naming.
            sd[base + "post_attention_layernorm.weight"] = (
                np_(layers["post_attn_norm"][i]) + noff
            )
            sd[base + "pre_feedforward_layernorm.weight"] = (
                np_(layers["mlp_norm"][i]) + noff
            )
            sd[base + "post_feedforward_layernorm.weight"] = (
                np_(layers["post_mlp_norm"][i]) + noff
            )
        else:
            sd[base + "post_attention_layernorm.weight"] = (
                np_(layers["mlp_norm"][i]) + noff
            )
    if cfg.tie_embeddings:
        sd["lm_head.weight"] = sd["model.embed_tokens.weight"]
    else:
        sd["lm_head.weight"] = np_(params["lm_head"]).T
    return sd


def from_hf(model_or_path, dtype=None):
    """(cfg, params) from a transformers model instance or local directory."""
    if isinstance(model_or_path, str):
        from transformers import AutoModelForCausalLM

        model = AutoModelForCausalLM.from_pretrained(
            model_or_path, local_files_only=True
        )
    else:
        model = model_or_path
    cfg = config_from_hf(model.config)
    params = params_from_state_dict(
        model.state_dict(), cfg, dtype=dtype,
        norm_offset=_norm_offset(model.config),
    )
    return cfg, params
