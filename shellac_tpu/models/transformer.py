"""Decoder-only transformer (LLaMA-style), functional and TPU-first.

Design choices, all driven by how XLA compiles for TPU:
  - Parameters are a plain pytree of arrays with a parallel pytree of
    *logical axis names* (see parallel/sharding.py). No module framework:
    pjit sees exactly the arrays and shardings we declare.
  - Layers are **stacked** along a leading axis and the forward pass is a
    `lax.scan` over them: one compiled block body regardless of depth
    (fast compiles), and the same stacked layout pipeline parallelism
    wants.
  - Each block is wrapped in `jax.checkpoint` when cfg.remat is set:
    activations are recomputed in backward, trading MXU FLOPs (cheap) for
    HBM (the scarce resource).
  - Compute in bf16, master params and softmax/norm accumulation in fp32.

The reference repo for this project is empty (SURVEY.md §0), so there is
no upstream architecture to cite; this is the standard pre-norm rotary
GQA decoder.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig
from shellac_tpu.ops.activations import geglu, softcap, swiglu
from shellac_tpu.ops.attention import attention
from shellac_tpu.ops.norms import rms_norm
from shellac_tpu.ops.qtrain import quant_dot
from shellac_tpu.ops.quant import materialize
from shellac_tpu.ops.rope import apply_rope, rope_angles
from shellac_tpu.parallel.sharding import constrain

Params = Dict[str, Any]


def grouped_moe(cfg: ModelConfig) -> bool:
    """True for interleaved dense/MoE stacks (moe_every > 1).

    Layout: layers are grouped into n_layers // moe_every super-blocks
    of (moe_every - 1) dense layers followed by one MoE layer (the
    DeepSeek/Mixtral-hybrid pattern, dense-first). Params hold two
    uniform stacks — {"dense": (ng, every-1, ...), "moe": (ng, ...)} —
    so the forward stays a scan over groups with a scan over the dense
    sub-stack inside: still one compiled block body per kind.
    """
    return cfg.moe is not None and cfg.moe_every > 1


def is_grouped_layers(layers) -> bool:
    """Structural twin of grouped_moe for code holding a params/axes
    layer tree but no config (merge helpers, axes mirrors)."""
    return set(layers.keys()) == {"dense", "moe"}


def first_k_layout(cfg: ModelConfig) -> bool:
    """True for the DeepSeek layout: a dense prefix then all-MoE.

    Params hold the same {"dense", "moe"} two-stack tree as the
    interleaved layout (so quantization/LoRA/sharding reuse applies
    unchanged), but the stacks are (first_k_dense, ...) and
    (n_layers - first_k_dense, ...) flat layer axes scanned back to
    back, not per-group sub-stacks.
    """
    return cfg.moe is not None and cfg.first_k_dense > 0


def _add_aux(a, b):
    return jax.tree.map(lambda u, v: u + v, a, b)


def _grouped_scan(blk_d, blk_m, x, aux0, glp_stack):
    """Scan an interleaved layout: per group, (every-1) dense blocks
    then one MoE block, accumulating aux. Shared by the plain forward
    and each pipeline stage (blk_* close over their RoPE/segment
    bindings)."""
    def group_body(carry, glp):
        x, acc = carry

        def dense_body(c2, lp):
            x2, acc2 = c2
            x2, _, mo = blk_d(x2, lp)
            return (x2, _add_aux(acc2, mo)), None

        (x, acc), _ = jax.lax.scan(dense_body, (x, acc), glp["dense"])
        x, _, mo = blk_m(x, glp["moe"])
        return (x, _add_aux(acc, mo)), None

    (x, acc), _ = jax.lax.scan(group_body, (x, aux0), glp_stack)
    return x, acc


def map_layer_stacks(layers, fn):
    """Apply `fn(stack, name)` to each per-layer stack of a layers tree.

    The single place that knows a layers tree is either one flat stack
    (name=None) or the {"dense", "moe"} sub-stacks of an interleaved
    layout — consumers (quantization, LoRA, sharding) use this instead
    of re-implementing the grouped branch.
    """
    if is_grouped_layers(layers):
        return {k: fn(layers[k], k) for k in ("dense", "moe")}
    return fn(layers, None)


def init_params(cfg: ModelConfig, key: jax.Array) -> Params:
    """Initialize a parameter pytree (master copy, cfg.param_dtype)."""
    cfg.validate()
    if grouped_moe(cfg) and cfg.n_layers % cfg.moe_every != 0:
        raise ValueError(
            f"n_layers={cfg.n_layers} must divide into groups of "
            f"moe_every={cfg.moe_every}"
        )
    pdt = cfg.params_dtype
    d, h, hkv, dh, f = (
        cfg.d_model, cfg.n_heads, cfg.kv_heads, cfg.dim_per_head, cfg.ff_dim,
    )
    k_embed, k_layers, k_head = jax.random.split(key, 3)

    def dense(key, shape, fan_in, scale=1.0):
        std = scale * fan_in ** -0.5
        return (jax.random.normal(key, shape, jnp.float32) * std).astype(pdt)

    def layer(key, moe_layer):
        ks = jax.random.split(key, 8)
        # Residual-output projections scaled down GPT-2 style so the
        # residual stream variance stays O(1) at depth.
        out_scale = (2 * cfg.n_layers) ** -0.5
        if cfg.mla is not None:
            m = cfg.mla
            kq = jax.random.split(ks[0], 2)
            kkv = jax.random.split(ks[1], 3)
            p = {
                "attn_norm": jnp.zeros((d,), pdt),
                "wkv_a": dense(kkv[0], (d, m.cache_dim), d),
                "kv_a_norm": jnp.zeros((m.kv_lora_rank,), pdt),
                "wkv_b_k": dense(
                    kkv[1], (m.kv_lora_rank, h, m.qk_nope_head_dim),
                    m.kv_lora_rank,
                ),
                "wkv_b_v": dense(
                    kkv[2], (m.kv_lora_rank, h, m.v_head_dim),
                    m.kv_lora_rank,
                ),
                "wo": dense(ks[3], (h * m.v_head_dim, d), h * m.v_head_dim,
                            out_scale),
                "mlp_norm": jnp.zeros((d,), pdt),
            }
            if m.q_lora_rank is None:
                p["wq"] = dense(kq[0], (d, h * m.qk_head_dim), d)
            else:
                p.update({
                    "wq_a": dense(kq[0], (d, m.q_lora_rank), d),
                    "q_a_norm": jnp.zeros((m.q_lora_rank,), pdt),
                    "wq_b": dense(kq[1], (m.q_lora_rank, h * m.qk_head_dim),
                                  m.q_lora_rank),
                })
        else:
            p = {
                "attn_norm": jnp.zeros((d,), pdt),
                "wq": dense(ks[0], (d, h * dh), d),
                "wk": dense(ks[1], (d, hkv * dh), d),
                "wv": dense(ks[2], (d, hkv * dh), d),
                "wo": dense(ks[3], (h * dh, d), h * dh, out_scale),
                "mlp_norm": jnp.zeros((d,), pdt),
            }
            if cfg.qk_norm:
                p.update({
                    "q_norm": jnp.zeros((dh,), pdt),
                    "k_norm": jnp.zeros((dh,), pdt),
                })
        if cfg.attn_bias:
            p.update({
                "bq": jnp.zeros((h * dh,), pdt),
                "bk": jnp.zeros((hkv * dh,), pdt),
                "bv": jnp.zeros((hkv * dh,), pdt),
            })
        if cfg.post_norms:
            p.update({
                "post_attn_norm": jnp.zeros((d,), pdt),
                "post_mlp_norm": jnp.zeros((d,), pdt),
            })
        if cfg.attn_sink:
            p["sinks"] = jnp.zeros((h,), pdt)
        if cfg.attn_out_bias:
            p["bo"] = jnp.zeros((d,), pdt)
        if not moe_layer:
            p.update({
                "w_gate": dense(ks[4], (d, f), d),
                "w_up": dense(ks[5], (d, f), d),
                "w_down": dense(ks[6], (f, d), f, out_scale),
            })
        else:
            e = cfg.moe.num_experts
            fe = cfg.moe.d_ff_expert or f
            p.update({
                "w_router": dense(ks[7], (d, e), d),
                "w_gate": dense(ks[4], (e, d, fe), d),
                "w_up": dense(ks[5], (e, d, fe), d),
                "w_down": dense(ks[6], (e, fe, d), fe, out_scale),
            })
            if cfg.moe.scoring in ("sigmoid", "softmax_topk"):
                p["b_router"] = jnp.zeros((e,), pdt)
            if cfg.moe.expert_bias:
                p.update({
                    "b_gate": jnp.zeros((e, fe), pdt),
                    "b_up": jnp.zeros((e, fe), pdt),
                    "b_down": jnp.zeros((e, d), pdt),
                })
            if cfg.moe.num_shared_experts > 0:
                sf = cfg.moe.num_shared_experts * fe
                ks2 = jax.random.split(ks[7], 4)
                p.update({
                    "w_gate_shared": dense(ks2[1], (d, sf), d),
                    "w_up_shared": dense(ks2[2], (d, sf), d),
                    "w_down_shared": dense(ks2[3], (sf, d), sf, out_scale),
                })
        return p

    if grouped_moe(cfg):
        every = cfg.moe_every
        ng = cfg.n_layers // every
        keys = jax.random.split(k_layers, cfg.n_layers).reshape(
            ng, every, -1
        )
        layers = {
            "dense": jax.vmap(jax.vmap(lambda k: layer(k, False)))(
                keys[:, : every - 1]
            ),
            "moe": jax.vmap(lambda k: layer(k, True))(keys[:, every - 1]),
        }
    elif first_k_layout(cfg):
        kk = cfg.first_k_dense
        keys = jax.random.split(k_layers, cfg.n_layers)
        layers = {
            "dense": jax.vmap(lambda k: layer(k, False))(keys[:kk]),
            "moe": jax.vmap(lambda k: layer(k, True))(keys[kk:]),
        }
    else:
        layer_keys = jax.random.split(k_layers, cfg.n_layers)
        layers = jax.vmap(lambda k: layer(k, cfg.moe is not None))(layer_keys)
    params: Params = {
        "embed": (jax.random.normal(k_embed, (cfg.vocab_size, d), jnp.float32)
                  * 0.02).astype(pdt),
        "layers": layers,
        "final_norm": jnp.zeros((d,), pdt),
    }
    if not cfg.tie_embeddings:
        params["lm_head"] = dense(k_head, (d, cfg.vocab_size), d)
    return params


def _layer_axes(cfg: ModelConfig, moe_layer: bool, lead=("layers",)) -> dict:
    """Axes for one layer stack; `lead` is the stacking prefix."""
    if not moe_layer:
        mlp_axes = {
            "w_gate": (*lead, "embed", "mlp"),
            "w_up": (*lead, "embed", "mlp"),
            "w_down": (*lead, "mlp", "embed"),
        }
    else:
        mlp_axes = {
            "w_router": (*lead, "embed", None),
            "w_gate": (*lead, "experts", "embed", "mlp"),
            "w_up": (*lead, "experts", "embed", "mlp"),
            "w_down": (*lead, "experts", "mlp", "embed"),
        }
        if cfg.moe.scoring in ("sigmoid", "softmax_topk"):
            mlp_axes["b_router"] = (*lead, None)
        if cfg.moe.expert_bias:
            mlp_axes.update({
                "b_gate": (*lead, "experts", "mlp"),
                "b_up": (*lead, "experts", "mlp"),
                "b_down": (*lead, "experts", "embed"),
            })
        if cfg.moe.num_shared_experts > 0:
            mlp_axes.update({
                "w_gate_shared": (*lead, "embed", "mlp"),
                "w_up_shared": (*lead, "embed", "mlp"),
                "w_down_shared": (*lead, "mlp", "embed"),
            })
    bias_axes = {}
    if cfg.attn_bias:
        bias_axes = {
            "bq": (*lead, "heads"),
            "bk": (*lead, "kv_heads"),
            "bv": (*lead, "kv_heads"),
        }
    if cfg.mla is not None:
        attn_axes = {
            # The latent projections are rank-bottlenecked, not
            # head-structured; only the per-head expansions and the
            # output projection shard over tp.
            "wkv_a": (*lead, "embed", None),
            "kv_a_norm": (*lead, None),
            "wkv_b_k": (*lead, None, "heads", None),
            "wkv_b_v": (*lead, None, "heads", None),
            "wo": (*lead, "heads", "embed"),
        }
        if cfg.mla.q_lora_rank is None:
            attn_axes["wq"] = (*lead, "embed", "heads")
        else:
            attn_axes.update({
                "wq_a": (*lead, "embed", None),
                "q_a_norm": (*lead, None),
                "wq_b": (*lead, None, "heads"),
            })
    else:
        attn_axes = {
            "wq": (*lead, "embed", "heads"),
            "wk": (*lead, "embed", "kv_heads"),
            "wv": (*lead, "embed", "kv_heads"),
            "wo": (*lead, "heads", "embed"),
        }
        if cfg.qk_norm:
            attn_axes.update({
                "q_norm": (*lead, None),
                "k_norm": (*lead, None),
            })
    post_axes = {}
    if cfg.post_norms:
        post_axes = {
            "post_attn_norm": (*lead, None),
            "post_mlp_norm": (*lead, None),
        }
    if cfg.attn_sink:
        post_axes["sinks"] = (*lead, "heads")
    if cfg.attn_out_bias:
        post_axes["bo"] = (*lead, None)
    return {
        "attn_norm": (*lead, None),
        **attn_axes,
        "mlp_norm": (*lead, None),
        **bias_axes,
        **post_axes,
        **mlp_axes,
    }


def logical_axes(cfg: ModelConfig) -> Params:
    """Pytree of logical axis names matching init_params' structure."""
    if grouped_moe(cfg):
        layers = {
            # Group axis maps like "layers" (pp shards it); the dense
            # sub-layer axis inside a group is unsharded.
            "dense": _layer_axes(cfg, False, lead=("layers", None)),
            "moe": _layer_axes(cfg, True),
        }
    elif first_k_layout(cfg):
        layers = {
            "dense": _layer_axes(cfg, False),
            "moe": _layer_axes(cfg, True),
        }
    else:
        layers = _layer_axes(cfg, cfg.moe is not None)
    la: Params = {
        "embed": ("vocab", "embed"),
        "layers": layers,
        "final_norm": (None,),
    }
    if not cfg.tie_embeddings:
        la["lm_head"] = ("embed", "vocab")
    return la


def _gated_act(cfg: ModelConfig):
    if cfg.activation == "swiglu":
        return swiglu
    if cfg.activation == "geglu":
        return geglu
    raise ValueError(
        f"unknown activation {cfg.activation!r}; have swiglu, geglu"
    )


def _embed_tokens(cfg: ModelConfig, params: Params, tokens, cdt, mesh=None):
    from shellac_tpu.parallel.mesh import AXIS_TENSOR

    if mesh is not None and mesh.shape.get(AXIS_TENSOR, 1) > 1:
        # The table's vocab axis is tp-sharded. A plain gather makes the
        # SPMD partitioner replicate the whole table every step
        # ("involuntary full rematerialization" warning); a one-hot
        # contraction keeps it sharded — the one-hot is built locally on
        # each shard, the contraction rides the MXU, and XLA inserts a
        # single psum over tp. Exact: one row of 1.0 per token, so the
        # bf16 sum adds zeros.
        one_hot = jax.nn.one_hot(tokens, cfg.vocab_size, dtype=cdt)
        x = jnp.einsum(
            "bsv,vd->bsd", one_hot, params["embed"].astype(cdt)
        )
    else:
        x = jnp.take(params["embed"], tokens, axis=0).astype(cdt)
    if cfg.embed_scale:
        # Gemma convention; the scale is computed in the compute dtype
        # (HF casts the normalizer to the embedding dtype too).
        x = x * jnp.asarray(cfg.d_model ** 0.5, cdt)
    return x


def _remat_policy(name: str):
    """Map ModelConfig.remat_policy to a jax.checkpoint saveable policy."""
    if name == "none":
        return None  # recompute everything (max memory savings)
    policies = {
        "dots": jax.checkpoint_policies.checkpoint_dots,
        "dots_no_batch": jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims,
    }
    if name not in policies:
        raise ValueError(
            f"unknown remat_policy {name!r}; have none, {sorted(policies)}"
        )
    return policies[name]


def _zero_aux():
    """Zero-valued MoE aux dict; the single source of its tree structure
    (the pipeline's aux accumulation requires every producer to match)."""
    zero = jnp.zeros((), jnp.float32)
    return {"aux": zero, "balance_loss": zero, "router_z_loss": zero,
            "dropped_frac": zero}


def _block(
    cfg: ModelConfig, mesh, attn_impl: str, x, lp, cos, sin, cache=None,
    fresh_cache: bool = False, segments=None, page_tables=None,
    moe_layer=None, kv_scales=None, attn_kind=None, rolled=False,
    new_len=None,
):
    """One pre-norm transformer block. x: (B, S, D) in compute dtype.

    With `cache=(cache_k, cache_v, index, q_positions)` the block runs in
    decode mode: new k/v are written at `index` and attention reads the
    whole cache; returns (x, (new_cache_k, new_cache_v)). Without cache
    it returns (x, None).

    fresh_cache=True asserts every sequence starts at index 0 (prefill
    into an empty cache): attention then runs causally over the new
    chunk itself — O(S^2/2) and flash-eligible — instead of scanning the
    whole max_len buffer, while k/v still land in the cache.

    attn_kind overrides cfg.attn_window per layer for patterned stacks
    (cfg.attn_pattern): "full" drops the window, "window"/None keep it.
    """
    window = None if attn_kind == "full" else cfg.attn_window
    cdt = cfg.compute_dtype
    b, s, d = x.shape
    h, hkv, dh = cfg.n_heads, cfg.kv_heads, cfg.dim_per_head

    def pdot(xin, w):
        # Dense projection: bf16 matmul, or an int8 MXU dot when the
        # training step opted in (cfg.quant_training, ops/qtrain.py).
        return quant_dot(xin, materialize(w, cdt), cfg.quant_training)

    # --- attention ---
    hx = rms_norm(x, lp["attn_norm"], cfg.norm_eps).astype(cdt)
    if cfg.mla is not None:
        o, new_cache = _mla_attention(
            cfg, mesh, attn_impl, hx, lp, cos, sin, cache,
            fresh_cache, segments, pdot, page_tables=page_tables,
            kv_scales=kv_scales,
        )
        o = pdot(o, lp["wo"])
        if cfg.post_norms:
            o = rms_norm(o, lp["post_attn_norm"], cfg.norm_eps).astype(cdt)
        x = x + constrain(o, mesh, ("batch", "seq", None))
        return _block_mlp(cfg, mesh, x, lp, pdot, cache, fresh_cache,
                          moe_layer, new_cache)
    q = pdot(hx, lp["wq"])
    k = pdot(hx, lp["wk"])
    v = pdot(hx, lp["wv"])
    if cfg.attn_bias:
        q = q + lp["bq"].astype(cdt)
        k = k + lp["bk"].astype(cdt)
        v = v + lp["bv"].astype(cdt)
    q = q.reshape(b, s, h, dh)
    k = k.reshape(b, s, hkv, dh)
    v = v.reshape(b, s, hkv, dh)
    if cfg.qk_norm:
        # Qwen3-style per-head-dim RMSNorm on q/k, applied before rope.
        q = rms_norm(q, lp["q_norm"], cfg.norm_eps).astype(cdt)
        k = rms_norm(k, lp["k_norm"], cfg.norm_eps).astype(cdt)
    q = apply_rope(q, cos, sin)
    k = apply_rope(k, cos, sin)
    sinks = lp["sinks"] if cfg.attn_sink else None
    new_cache = None
    if cache is None:
        o = _training_attention(cfg, mesh, attn_impl, q, k, v, segments,
                                window=window, sinks=sinks)
    elif page_tables is not None:
        from shellac_tpu.inference.kvcache import (
            paged_update_layer,
            quant_paged_update_layer,
        )

        pool_k, pool_v, index, q_positions = cache  # pool: (nb, Hkv, bs, D)
        if kv_scales is not None:
            # Int8 pool: quantize at write (K post-rope, the
            # QuantKVCache contract); scale pools scatter through the
            # same block tables.
            ks_l, vs_l = kv_scales
            pool_k, pool_v, ks_l, vs_l = quant_paged_update_layer(
                pool_k, pool_v, ks_l, vs_l, k, v, index, page_tables
            )
            new_cache = (pool_k, pool_v, ks_l, vs_l)
        else:
            ks_l = vs_l = None
            pool_k, pool_v = paged_update_layer(
                pool_k, pool_v, k, v, index, page_tables
            )
            new_cache = (pool_k, pool_v)
        if fresh_cache:
            o = attention(
                q, k, v, causal=True, window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks,
            )
        else:
            from shellac_tpu.ops.decode_attention import (
                paged_decode_attention,
            )

            o = paged_decode_attention(
                q, pool_k, pool_v, page_tables, index,
                window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks, k_scale=ks_l, v_scale=vs_l,
            )
    elif rolled:
        from shellac_tpu.inference.kvcache import (
            quant_roll_update_layer,
            roll_update_layer,
        )
        from shellac_tpu.ops.decode_attention import (
            rolled_decode_attention,
        )

        cache_k, cache_v, index, q_positions = cache  # ring buffers
        if kv_scales is not None:
            # Int8 ring: quantize at write (K post-rope, the QuantKVCache
            # contract); reads dequantize the window-sized ring.
            ks_l, vs_l = kv_scales
            cache_k, cache_v, ks_l, vs_l = quant_roll_update_layer(
                cache_k, cache_v, ks_l, vs_l, k, v, index,
                valid_len=new_len,
            )
            new_cache = (cache_k, cache_v, ks_l, vs_l)
        else:
            cache_k, cache_v = roll_update_layer(
                cache_k, cache_v, k, v, index, valid_len=new_len
            )
            new_cache = (cache_k, cache_v)
        if fresh_cache:
            # Whole-prompt prefill attends the incoming chunk itself
            # (exact values — identical to the dense path); the ring
            # only matters for later reads.
            o = attention(
                q, k, v, causal=True, window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks,
            )
        else:
            rk, rv = cache_k, cache_v
            if kv_scales is not None:
                # Dequantize IN fp32 and stay there: a cast to the
                # compute dtype would add a rounding the dense int8
                # path never pays (its kernel folds the fp32 scale
                # after the integer dot).
                rk = rk.astype(jnp.float32) * ks_l[..., None]
                rv = rv.astype(jnp.float32) * vs_l[..., None]
            vl = s if new_len is None else new_len
            o = rolled_decode_attention(
                q, rk, rv, index, index + vl, window=window,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks,
            )
    elif kv_scales is not None:
        from shellac_tpu.inference.kvcache import quant_update_layer
        from shellac_tpu.ops.decode_attention import decode_attention

        cache_k, cache_v, index, q_positions = cache  # int8 cache layer
        ks_l, vs_l = kv_scales
        cache_k, cache_v, ks_l, vs_l = quant_update_layer(
            cache_k, cache_v, ks_l, vs_l, k, v, index
        )
        new_cache = (cache_k, cache_v, ks_l, vs_l)
        if fresh_cache:
            # Prefill computes on the exact (unquantized) chunk; only
            # later reads see the int8 rounding.
            o = attention(
                q, k, v, causal=True, window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks,
            )
        else:
            o = decode_attention(
                q, cache_k, cache_v, index,
                window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks, k_scale=ks_l, v_scale=vs_l,
            )
    else:
        from shellac_tpu.inference.kvcache import update_layer

        cache_k, cache_v, index, q_positions = cache  # index: (B,)
        cache_k, cache_v = update_layer(cache_k, cache_v, k, v, index)
        new_cache = (cache_k, cache_v)
        if fresh_cache:
            # Empty-cache prefill: attend within the new chunk only.
            # Every row's positions start at 0, so plain causal masking
            # already excludes the right-pad tail of shorter prompts.
            o = attention(
                q, k, v, causal=True, window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks,
            )
        else:
            from shellac_tpu.ops.decode_attention import decode_attention

            o = decode_attention(
                q, cache_k, cache_v, index,
                window=window, impl=attn_impl,
                scale=cfg.attn_scale, softcap=cfg.attn_softcap,
                sinks=sinks,
            )
    o = pdot(o.reshape(b, s, h * dh), lp["wo"])
    if cfg.attn_out_bias:
        o = o + lp["bo"].astype(cdt)
    if cfg.post_norms:
        # Gemma-2 sandwich norm: the branch OUTPUT is normed before the
        # residual add (HF post_attention_layernorm placement).
        o = rms_norm(o, lp["post_attn_norm"], cfg.norm_eps).astype(cdt)
    x = x + constrain(o, mesh, ("batch", "seq", None))
    return _block_mlp(cfg, mesh, x, lp, pdot, cache, fresh_cache,
                      moe_layer, new_cache)


def _block_mlp(cfg, mesh, x, lp, pdot, cache, fresh_cache, moe_layer,
               new_cache):
    """The MLP half of a block (shared by the MHA/GQA and MLA paths)."""
    cdt = cfg.compute_dtype
    hx = rms_norm(x, lp["mlp_norm"], cfg.norm_eps).astype(cdt)
    moe_out = _zero_aux()
    # moe_layer overrides the config for interleaved stacks (grouped_moe):
    # dense sub-layers of a MoE model run the plain gated MLP.
    use_moe = cfg.moe is not None if moe_layer is None else moe_layer
    if use_moe:
        from shellac_tpu.ops.moe import moe_ffn

        # Cached continuation (decode s=1, speculative verify windows,
        # prefix-cached suffix prefill) must never capacity-drop: a
        # dropped token's FFN output would silently become zero, and
        # decode-path exactness is the serving contract. Only fresh
        # prefill keeps routed capacity (unless cfg.moe.dropless asks
        # for exact computation everywhere, or grouped_dropless picks
        # the sorted-segment training path).
        is_decode = cache is not None and not fresh_cache
        # Strict lookups for biased gates: a missing bias must be a
        # loud KeyError, not a silent zero (it changes which experts
        # are selected / what they compute).
        bias_kw = dict(
            b_router=(lp["b_router"]
                      if cfg.moe.scoring in ("sigmoid", "softmax_topk")
                      else None),
            b_gate=lp["b_gate"] if cfg.moe.expert_bias else None,
            b_up=lp["b_up"] if cfg.moe.expert_bias else None,
            b_down=lp["b_down"] if cfg.moe.expert_bias else None,
        )
        if cfg.moe.grouped_dropless and not is_decode:
            from shellac_tpu.ops.moe import moe_ffn_grouped

            down, aux, metrics = moe_ffn_grouped(
                hx, lp["w_router"], lp["w_gate"], lp["w_up"],
                lp["w_down"], cfg.moe, mesh=mesh, **bias_kw,
            )
        else:
            down, aux, metrics = moe_ffn(
                hx, lp["w_router"], lp["w_gate"], lp["w_up"],
                lp["w_down"], cfg.moe,
                drop_tokens=not (is_decode or cfg.moe.dropless),
                mesh=mesh, **bias_kw,
            )
        if cfg.moe.num_shared_experts > 0:
            sg = hx @ materialize(lp["w_gate_shared"], cdt)
            su = hx @ materialize(lp["w_up_shared"], cdt)
            down = down + _gated_act(cfg)(sg, su) @ materialize(
                lp["w_down_shared"], cdt
            )
        moe_out = {
            "aux": aux,
            "balance_loss": metrics["moe_balance_loss"],
            "router_z_loss": metrics["moe_router_z_loss"],
            "dropped_frac": metrics["moe_dropped_frac"],
        }
    else:
        gate = pdot(hx, lp["w_gate"])
        up = pdot(hx, lp["w_up"])
        gate = constrain(gate, mesh, ("batch", "seq", "mlp"))
        up = constrain(up, mesh, ("batch", "seq", "mlp"))
        down = pdot(_gated_act(cfg)(gate, up), lp["w_down"])
    if cfg.post_norms:
        down = rms_norm(down, lp["post_mlp_norm"], cfg.norm_eps).astype(cdt)
    x = x + constrain(down, mesh, ("batch", "seq", None))
    return x, new_cache, moe_out


def _training_attention(cfg, mesh, attn_impl, q, k, v, segments,
                        window="cfg", sinks=None):
    """Full-sequence attention with sequence-parallel dispatch.

    q (B, S, H, D); k/v (B, S, Hkv, D'). Shared by the standard GQA
    path and MLA's expanded form (there Hkv == H and v is padded to
    q's width, so the default d**-0.5 scale is already the MLA scale).
    `window` overrides cfg.attn_window for patterned stacks (the "cfg"
    sentinel keeps MLA's call sites untouched).
    """
    if window == "cfg":
        window = cfg.attn_window
    h, hkv = q.shape[2], k.shape[2]
    q = constrain(q, mesh, ("batch", "seq", "heads", None))
    k = constrain(k, mesh, ("batch", "seq", "kv_heads", None))
    v = constrain(v, mesh, ("batch", "seq", "kv_heads", None))
    from shellac_tpu.parallel.mesh import AXIS_SEQ

    sp_active = mesh is not None and mesh.shape.get(AXIS_SEQ, 1) > 1
    if attn_impl in ("ring", "ulysses") and not sp_active:
        raise ValueError(
            f"attn_impl={attn_impl!r} requires a mesh with sp > 1; got "
            f"mesh={'None' if mesh is None else dict(mesh.shape)}"
        )
    from shellac_tpu.parallel.ulysses import ulysses_supported

    ulysses_ok = sp_active and ulysses_supported(h, hkv, mesh)
    if attn_impl == "ulysses" and not ulysses_ok:
        raise ValueError(
            f"attn_impl='ulysses' needs per-device head counts divisible "
            f"by sp: n_heads={h}, n_kv_heads={hkv}, "
            f"mesh={dict(mesh.shape)}"
        )
    # 'auto' on an sp mesh: ring for plain causal (O(S/sp) kv
    # memory), ulysses for windowed attention when head counts
    # permit (full local sequence -> the flash kernel's window
    # block-skipping applies); ring handles windows too (banded
    # mask on global positions), so it is the windowed fallback
    # when ulysses can't split the heads.
    use_ulysses = attn_impl == "ulysses" or (
        attn_impl == "auto" and sp_active and window is not None
        and ulysses_ok
    )
    use_ring = attn_impl == "ring" or (
        attn_impl == "auto" and sp_active and not use_ulysses
    )
    if use_ring:
        # Sequence is sharded over sp: ring attention keeps kv local
        # (O(S/sp) memory) and rotates chunks over ICI instead of
        # letting GSPMD all-gather the whole sequence. Packed
        # segment ids rotate with their kv chunks.
        from shellac_tpu.parallel.ring_attention import ring_attention

        return ring_attention(
            q, k, v, mesh, causal=cfg.causal, segments=segments,
            window=window, scale=cfg.attn_scale, softcap=cfg.attn_softcap,
            sinks=sinks,
        )
    if use_ulysses:
        from shellac_tpu.parallel.ulysses import ulysses_attention

        return ulysses_attention(
            q, k, v, mesh, causal=cfg.causal, window=window,
            scale=cfg.attn_scale, softcap=cfg.attn_softcap,
            sinks=sinks, segments=segments,
        )
    return attention(
        q, k, v, causal=cfg.causal, window=window,
        scale=cfg.attn_scale, softcap=cfg.attn_softcap, sinks=sinks,
        q_segments=segments, kv_segments=segments, impl=attn_impl,
    )


def _mla_attention(
    cfg: ModelConfig, mesh, attn_impl, hx, lp, cos, sin, cache,
    fresh_cache, segments, pdot, page_tables=None, kv_scales=None,
):
    """Multi-head latent attention (DeepSeek-style). Returns
    (o (B, S, H*v_head_dim), new_cache-or-None).

    Numerics follow HF DeepseekV2Attention exactly (interleaved rope on
    the qk_rope slice, shared single-head roped key, softmax scale
    qk_head_dim**-0.5). The cached path is the TPU-first part: the
    cache holds ONE row per token — concat(normed latent, roped k_pe),
    `kv_lora_rank + qk_rope_head_dim` wide, no head axis — and decode
    uses matrix absorption: scores contract the latent against
    per-head-projected queries (q_nope @ W_bk), and values re-expand
    AFTER the weighted sum (attn @ latent, then W_bv). That is exact
    algebra, not an approximation, and shrinks the cache ~n_heads-fold
    vs materializing K/V (HF's cache stores the expanded tensors).
    """
    from shellac_tpu.ops.rope import apply_rope_interleaved

    m = cfg.mla
    cdt = cfg.compute_dtype
    b, s, _ = hx.shape
    h = cfg.n_heads
    scale = m.qk_head_dim ** -0.5

    if m.q_lora_rank is None:
        q = pdot(hx, lp["wq"])
    else:
        qa = rms_norm(
            pdot(hx, lp["wq_a"]), lp["q_a_norm"], cfg.norm_eps
        ).astype(cdt)
        q = pdot(qa, lp["wq_b"])
    q = q.reshape(b, s, h, m.qk_head_dim)
    q = constrain(q, mesh, ("batch", "seq", "heads", None))
    q_nope = q[..., : m.qk_nope_head_dim]
    q_pe = apply_rope_interleaved(q[..., m.qk_nope_head_dim:], cos, sin)

    ckv = pdot(hx, lp["wkv_a"])  # (b, s, kv_rank + rope)
    c = rms_norm(
        ckv[..., : m.kv_lora_rank], lp["kv_a_norm"], cfg.norm_eps
    ).astype(cdt)
    k_pe = apply_rope_interleaved(
        ckv[..., None, m.kv_lora_rank:], cos, sin
    )  # (b, s, 1, rope)

    w_bk = materialize(lp["wkv_b_k"], cdt)  # (kv_rank, h, nope)
    w_bv = materialize(lp["wkv_b_v"], cdt)  # (kv_rank, h, v_dim)

    def expanded_attention():
        """Full-K/V form (training and fresh prefill): expand the
        latent per head, pad v up to the qk width so the flash kernel
        applies, slice the pad back off. Dispatches through the shared
        sequence-parallel selection (ring/ulysses on sp meshes), where
        the default q-width scale IS the MLA scale."""
        k_nope = jnp.einsum("bsr,rhn->bshn", c, w_bk)
        v = jnp.einsum("bsr,rhv->bshv", c, w_bv)
        k = jnp.concatenate(
            [k_nope, jnp.broadcast_to(k_pe, (b, s, h, m.qk_rope_head_dim))],
            axis=-1,
        )
        qf = jnp.concatenate([q_nope, q_pe], axis=-1)
        pad = m.qk_head_dim - m.v_head_dim
        vp = jnp.pad(v, ((0, 0), (0, 0), (0, 0), (0, pad)))
        o = _training_attention(cfg, mesh, attn_impl, qf, k, vp, segments)
        return o[..., : m.v_head_dim]

    if cache is None:
        o = expanded_attention()
        return o.reshape(b, s, h * m.v_head_dim), None

    def absorbed_q():
        """Per-head queries projected into latent space + the roped
        slice: MQA rows against the latent cache."""
        q_eff = jnp.einsum("bshn,rhn->bshr", q_nope, w_bk)
        return jnp.concatenate([q_eff, q_pe], axis=-1)

    latent = jnp.concatenate([c[:, :, None, :], k_pe], axis=-1)  # (b,s,1,·)
    v_stub = jnp.zeros((b, s, 1, 0), cdt)

    if page_tables is not None:
        from shellac_tpu.inference.kvcache import (
            paged_update_layer,
            quant_paged_update_layer,
        )
        from shellac_tpu.ops.decode_attention import paged_decode_attention

        pool_k, pool_v, index, _ = cache
        if kv_scales is not None:
            # Int8 latent pool: one scale per latent row, serving both
            # attention roles like the dense int8 latent cache. (The
            # latent width is not 128-aligned, so reads take the
            # gather + dequant reference path — correct, with the
            # paged-fallback warning naming the constraint.)
            ks_l, vs_l = kv_scales
            pool_k, pool_v, ks_l, vs_l = quant_paged_update_layer(
                pool_k, pool_v, ks_l, vs_l, latent, v_stub, index,
                page_tables,
            )
            new_cache = (pool_k, pool_v, ks_l, vs_l)
        else:
            ks_l = None
            pool_k, pool_v = paged_update_layer(
                pool_k, pool_v, latent, v_stub, index, page_tables
            )
            new_cache = (pool_k, pool_v)
        if fresh_cache:
            o = expanded_attention()
        else:
            # Same k-as-v trick as the dense path: the latent pool
            # serves both roles, values are its first kv_rank lanes.
            o_lat = paged_decode_attention(
                absorbed_q(), pool_k, pool_k, page_tables, index,
                scale=scale, impl=attn_impl,
                k_scale=ks_l, v_scale=ks_l,
            )[..., : m.kv_lora_rank]
            o = jnp.einsum("bshr,rhv->bshv", o_lat, w_bv)
        return o.reshape(b, s, h * m.v_head_dim), new_cache

    from shellac_tpu.ops.decode_attention import decode_attention

    cache_k, cache_v, index, _ = cache
    if kv_scales is not None:
        # Int8 latent cache: one scale per latent row; the k array (and
        # its scale) serves both attention roles, like the bf16 path.
        from shellac_tpu.inference.kvcache import quant_update_layer

        ks_l, vs_l = kv_scales
        cache_k, cache_v, ks_l, vs_l = quant_update_layer(
            cache_k, cache_v, ks_l, vs_l, latent, v_stub, index
        )
        new_cache = (cache_k, cache_v, ks_l, vs_l)
        if fresh_cache:
            o = expanded_attention()
        else:
            o_lat = decode_attention(
                absorbed_q(), cache_k, cache_k, index, scale=scale,
                impl=attn_impl, k_scale=ks_l, v_scale=ks_l,
            )[..., : m.kv_lora_rank]
            o = jnp.einsum("bshr,rhv->bshv", o_lat, w_bv)
        return o.reshape(b, s, h * m.v_head_dim), new_cache

    from shellac_tpu.inference.kvcache import update_layer

    cache_k, cache_v = update_layer(cache_k, cache_v, latent, v_stub, index)
    new_cache = (cache_k, cache_v)
    if fresh_cache:
        o = expanded_attention()
    else:
        # Absorbed decode: MQA over the latent rows. The same cache
        # array serves as k AND v (values are its first kv_rank lanes
        # after the weighted sum), so no second copy is ever stored.
        o_lat = decode_attention(
            absorbed_q(), cache_k, cache_k, index, scale=scale,
            impl=attn_impl,
        )[..., : m.kv_lora_rank]
        o = jnp.einsum("bshr,rhv->bshv", o_lat, w_bv)
    return o.reshape(b, s, h * m.v_head_dim), new_cache


def segment_positions(segment_ids: jax.Array) -> jax.Array:
    """Per-segment position ids: restart at 0 on every segment change.

    segment_ids: (B, S) int32, non-decreasing along S within a row.
    """
    b, s = segment_ids.shape
    ar = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    changed = jnp.concatenate(
        [jnp.ones((b, 1), bool), segment_ids[:, 1:] != segment_ids[:, :-1]],
        axis=1,
    )
    start = jax.lax.cummax(jnp.where(changed, ar, 0), axis=1)
    return ar - start


def forward(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,  # (B, S) int32
    *,
    positions: Optional[jax.Array] = None,  # (B, S) int32
    segment_ids: Optional[jax.Array] = None,  # (B, S) int32 — packed docs
    mesh=None,
    attn_impl: str = "auto",
    pipeline_microbatches: Optional[int] = None,
    return_aux: bool = False,
    return_hidden: bool = False,
) -> jax.Array:
    """Full forward pass; returns fp32 logits (B, S, V).

    With return_hidden=True, skips the LM head and returns the
    post-final-norm hidden states (B, S, D) in compute dtype instead of
    logits — the seam the fused (vocab-chunked) loss uses so the full
    logits tensor never materializes.

    With a mesh whose pp axis > 1, the layer stack runs as a GPipe
    pipeline with `pipeline_microbatches` microbatches (default pp).
    With segment_ids, rows hold multiple packed documents: attention is
    block-diagonal over segments and RoPE positions restart per segment,
    so each document computes exactly as if it were alone in the row.
    With return_aux=True, returns (logits, aux) where aux is a dict:
    "aux" (summed MoE auxiliary loss, 0 for dense) plus per-layer-mean
    router diagnostics (balance_loss, router_z_loss, dropped_frac).
    """
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    pos = positions
    if pos is None:
        if segment_ids is not None:
            pos = segment_positions(segment_ids)
        else:
            pos = jnp.broadcast_to(jnp.arange(s, dtype=jnp.int32), (b, s))
    cos, sin = rope_angles(pos, cfg.rope_dim, cfg.rope_theta,
                           yarn=cfg.rope_yarn, llama3=cfg.rope_llama3,
                           linear=cfg.rope_linear)
    if cfg.rope_local_theta is not None:
        # Gemma-3 dual rope: "window" layers use their own unscaled
        # frequency base; rope scaling applies to global layers only.
        cos_l, sin_l = rope_angles(pos, cfg.rope_dim, cfg.rope_local_theta)
    else:
        cos_l = sin_l = None

    x = _embed_tokens(cfg, params, tokens, cdt, mesh=mesh)
    x = constrain(x, mesh, ("batch", "seq", None))

    if segment_ids is not None and mesh is not None:
        # Replicate the segment row over sp ONCE, outside the layer
        # scan: both sp attention paths want non-seq-sharded views of it
        # (ulysses needs the full row on every rank; ring slices its
        # chunk inside shard_map), and without this constraint GSPMD
        # would place the sp all-gather at the shard_map boundary inside
        # the scan body — one collective per layer for layer-invariant
        # int32 ids.
        segment_ids = constrain(segment_ids, mesh, ("batch", None))

    def make_block(moe_flag, attn_kind=None):
        blk = functools.partial(
            _block, cfg, mesh, attn_impl, segments=segment_ids,
            moe_layer=moe_flag, attn_kind=attn_kind,
        )
        if cfg.remat:
            blk = jax.checkpoint(blk, policy=_remat_policy(cfg.remat_policy))
        return blk

    from shellac_tpu.parallel.mesh import AXIS_PIPE

    pp = mesh.shape.get(AXIS_PIPE, 1) if mesh is not None else 1
    if pp > 1:
        from shellac_tpu.parallel.pipeline import pipeline_apply

        if first_k_layout(cfg):
            raise NotImplementedError(
                "pp over a first_k_dense layout is not wired yet (the "
                "two stacks are unequal; stage balancing needs its own "
                "schedule) — use pp=1 or the moe_every layout"
            )
        if grouped_moe(cfg):
            # Interleaved stacks pipeline at GROUP granularity: each
            # stage holds whole (dense^(every-1), moe) super-blocks, so
            # stage compute stays uniform and the group axis shards
            # over pp exactly like the layer axis does for flat stacks.
            ng = cfg.n_layers // cfg.moe_every
            if ng % pp:
                raise ValueError(
                    f"n_layers/moe_every = {ng} groups not divisible "
                    f"by pp={pp}"
                )
            per_stage = ng // pp
        else:
            if cfg.n_layers % pp:
                raise ValueError(
                    f"n_layers={cfg.n_layers} not divisible by pp={pp}"
                )
            per_stage = cfg.n_layers // pp
            if cfg.attn_pattern is not None and \
                    per_stage % len(cfg.attn_pattern):
                raise ValueError(
                    f"pp={pp} stages hold {per_stage} layers each, not a "
                    f"whole number of attn_pattern periods "
                    f"(len {len(cfg.attn_pattern)})"
                )
        stage_params = jax.tree.map(
            lambda p: p.reshape(pp, per_stage, *p.shape[1:]),
            params["layers"],
        )

        aux0 = _zero_aux()

        # The block partial above binds the whole-batch segment row;
        # microbatches see a slice of the batch, so the pipeline needs
        # unbound blocks whose RoPE tables / segment ids ride WITH
        # each microbatch through the stage shift register.
        def make_pp_block(moe_flag, attn_kind=None):
            def raw(x, lp, cos_m, sin_m, seg_m):
                return _block(
                    cfg, mesh, attn_impl, x, lp, cos_m, sin_m,
                    segments=seg_m, moe_layer=moe_flag, attn_kind=attn_kind,
                )

            if cfg.remat:
                return jax.checkpoint(
                    raw, policy=_remat_policy(cfg.remat_policy)
                )
            return raw



        ragged = positions is not None or segment_ids is not None
        if ragged:
            extras = {"cos": cos, "sin": sin}
            extras_axes = {
                "cos": ("batch", "seq", None),
                "sin": ("batch", "seq", None),
            }
            if cos_l is not None:
                extras.update({"cos_l": cos_l, "sin_l": sin_l})
                extras_axes.update({
                    "cos_l": ("batch", "seq", None),
                    "sin_l": ("batch", "seq", None),
                })
            if segment_ids is not None:
                # Keep the sp replication set up above: sharding seg
                # over "seq" here would reintroduce the per-layer sp
                # all-gather inside every pipeline tick.
                extras["seg"] = segment_ids
                extras_axes["seg"] = ("batch", None)
        else:
            extras = extras_axes = None
            # Uniform positions: a (1, S, half) table broadcasts over
            # every microbatch — cheaper than shifting per-row tables.
            cos, sin = cos[:1], sin[:1]
            if cos_l is not None:
                cos_l, sin_l = cos_l[:1], sin_l[:1]

        if grouped_moe(cfg):
            pp_blk_d = make_pp_block(False)
            pp_blk_m = make_pp_block(True)

            def run_stack(sp_glp, x, cos_m, sin_m, seg_m,
                          cos_lm=None, sin_lm=None):
                # sp_glp: this stage's groups — {"dense": (Gs, every-1,
                # ...), "moe": (Gs, ...)}.
                def blk_d(x, lp):
                    return pp_blk_d(x, lp, cos_m, sin_m, seg_m)

                def blk_m(x, lp):
                    return pp_blk_m(x, lp, cos_m, sin_m, seg_m)

                return _grouped_scan(blk_d, blk_m, x, aux0, sp_glp)
        elif cfg.attn_pattern is not None:
            period = len(cfg.attn_pattern)
            pp_blocks = [make_pp_block(None, kind)
                         for kind in cfg.attn_pattern]

            def run_stack(sp_lp, x, cos_m, sin_m, seg_m,
                          cos_lm=None, sin_lm=None):
                # sp_lp: (per_stage, ...) -> (groups, period, ...);
                # the scan walks groups, the pattern unrolls inside (a
                # window is a static kernel argument, so each kind
                # compiles its own block body).
                glp = jax.tree.map(
                    lambda a: a.reshape(
                        a.shape[0] // period, period, *a.shape[1:]
                    ),
                    sp_lp,
                )

                def body(carry, gl):
                    x, acc = carry
                    for i, blk in enumerate(pp_blocks):
                        lp_i = jax.tree.map(lambda a, i=i: a[i], gl)
                        local = (cos_lm is not None
                                 and cfg.attn_pattern[i] == "window")
                        x, _, moe_out = blk(
                            x, lp_i, cos_lm if local else cos_m,
                            sin_lm if local else sin_m, seg_m,
                        )
                        acc = _add_aux(acc, moe_out)
                    return (x, acc), None

                (x, acc), _ = jax.lax.scan(body, (x, aux0), glp)
                return x, acc
        else:
            pp_block = make_pp_block(None)

            def run_stack(sp_lp, x, cos_m, sin_m, seg_m,
                          cos_lm=None, sin_lm=None):
                def body(carry, lp):
                    x, acc = carry
                    x, _, moe_out = pp_block(x, lp, cos_m, sin_m, seg_m)
                    return (x, _add_aux(acc, moe_out)), None

                (x, acc), _ = jax.lax.scan(body, (x, aux0), sp_lp)
                return x, acc

        if ragged:
            def stage_fn(sp_lp, x, ex):
                return run_stack(
                    sp_lp, x, ex["cos"], ex["sin"], ex.get("seg"),
                    ex.get("cos_l"), ex.get("sin_l"),
                )
        else:
            def stage_fn(sp_lp, x):
                return run_stack(sp_lp, x, cos, sin, None, cos_l, sin_l)

        n_micro = pipeline_microbatches or pp
        x, aux_sum = pipeline_apply(
            stage_fn, stage_params, x,
            n_stages=pp, n_micro=n_micro, mesh=mesh, aux_init=aux0,
            extras=extras, extras_axes=extras_axes,
        )
        # aux_sum holds every (layer, microbatch) contribution once.
        # The aux loss is the per-microbatch estimate averaged over
        # microbatches (each micro's balance loss is computed on its own
        # token population — the standard grad-accum estimator);
        # diagnostics additionally average over layers.
        inv_m = 1.0 / n_micro
        # Diagnostics average over the layers that actually have
        # routers: every layer for uniform MoE, one per group for
        # interleaved stacks.
        routers = (cfg.n_layers // cfg.moe_every if grouped_moe(cfg)
                   else cfg.n_layers)
        inv_lm = inv_m / routers
        aux = {
            "aux": aux_sum["aux"] * inv_m,
            "balance_loss": aux_sum["balance_loss"] * inv_lm,
            "router_z_loss": aux_sum["router_z_loss"] * inv_lm,
            "dropped_frac": aux_sum["dropped_frac"] * inv_lm,
        }
    elif grouped_moe(cfg):
        aux0 = _zero_aux()
        bd, bm = make_block(False), make_block(True)
        x, aux_acc = _grouped_scan(
            lambda x, lp: bd(x, lp, cos, sin),
            lambda x, lp: bm(x, lp, cos, sin),
            x, aux0, params["layers"],
        )
        # Aux loss sums over MoE layers; diagnostics average over the
        # layers that actually have routers (one per group).
        inv_l = cfg.moe_every / cfg.n_layers
        aux = {
            "aux": aux_acc["aux"],
            "balance_loss": aux_acc["balance_loss"] * inv_l,
            "router_z_loss": aux_acc["router_z_loss"] * inv_l,
            "dropped_frac": aux_acc["dropped_frac"] * inv_l,
        }
    elif first_k_layout(cfg):
        aux0 = _zero_aux()
        bd, bm = make_block(False), make_block(True)

        def stack_body(blk):
            def body(carry, lp):
                x, acc = carry
                x, _, mo = blk(x, lp, cos, sin)
                return (x, _add_aux(acc, mo)), None

            return body

        (x, acc), _ = jax.lax.scan(
            stack_body(bd), (x, aux0), params["layers"]["dense"]
        )
        (x, aux_acc), _ = jax.lax.scan(
            stack_body(bm), (x, acc), params["layers"]["moe"]
        )
        routers = cfg.n_layers - cfg.first_k_dense
        aux = {
            "aux": aux_acc["aux"],
            "balance_loss": aux_acc["balance_loss"] / routers,
            "router_z_loss": aux_acc["router_z_loss"] / routers,
            "dropped_frac": aux_acc["dropped_frac"] / routers,
        }
    elif cfg.attn_pattern is not None:
        # Patterned attention (Gemma-2/3 alternating local/global): the
        # flat (L, ...) stack reshapes to (L/period, period, ...) and the
        # scan walks whole periods, unrolling the kinds inside — window
        # size is a static kernel argument, so each kind needs its own
        # compiled block body, but params/checkpoints keep the flat
        # layers axis (sharding, LoRA, conversion are unchanged).
        aux0 = _zero_aux()
        period = len(cfg.attn_pattern)
        blocks = [make_block(None, kind) for kind in cfg.attn_pattern]
        glp = jax.tree.map(
            lambda a: a.reshape(
                a.shape[0] // period, period, *a.shape[1:]
            ),
            params["layers"],
        )

        def group_body(carry, gl):
            x, acc = carry
            for i, blk in enumerate(blocks):
                lp_i = jax.tree.map(lambda a, i=i: a[i], gl)
                local = (cos_l is not None
                         and cfg.attn_pattern[i] == "window")
                x, _, moe_out = blk(
                    x, lp_i, cos_l if local else cos,
                    sin_l if local else sin,
                )
                acc = _add_aux(acc, moe_out)
            return (x, acc), None

        (x, aux_acc), _ = jax.lax.scan(group_body, (x, aux0), glp)
        inv_l = 1.0 / cfg.n_layers
        aux = {
            "aux": aux_acc["aux"],
            "balance_loss": aux_acc["balance_loss"] * inv_l,
            "router_z_loss": aux_acc["router_z_loss"] * inv_l,
            "dropped_frac": aux_acc["dropped_frac"] * inv_l,
        }
    else:
        aux0 = _zero_aux()
        block = make_block(None)

        def scan_body(carry, lp):
            x, acc = carry
            x, _, moe_out = block(x, lp, cos, sin)
            acc = jax.tree.map(lambda a, b: a + b, acc, moe_out)
            return (x, acc), None

        (x, aux_acc), _ = jax.lax.scan(scan_body, (x, aux0), params["layers"])
        # Aux loss sums over layers; diagnostics average.
        inv_l = 1.0 / cfg.n_layers
        aux = {
            "aux": aux_acc["aux"],
            "balance_loss": aux_acc["balance_loss"] * inv_l,
            "router_z_loss": aux_acc["router_z_loss"] * inv_l,
            "dropped_frac": aux_acc["dropped_frac"] * inv_l,
        }

    if return_hidden:
        x = rms_norm(x, params["final_norm"], cfg.norm_eps).astype(cdt)
        x = constrain(x, mesh, ("batch", "seq", None))
        if return_aux:
            return x, aux
        return x
    logits = unembed(cfg, params, x)
    logits = constrain(logits, mesh, ("batch", "seq", "vocab"))
    if return_aux:
        return logits, aux
    return logits


def output_weights(cfg: ModelConfig, params: Params, cdt) -> jax.Array:
    """The LM-head matrix (D, V) in compute dtype (tied or untied)."""
    if cfg.tie_embeddings:
        return params["embed"].astype(cdt).T
    return params["lm_head"].astype(cdt)


def unembed(cfg: ModelConfig, params: Params, x: jax.Array) -> jax.Array:
    """Final RMSNorm + output projection (+ logit softcap): the model
    tail shared by forward, forward_with_cache, and the pipelined
    decode's per-group exit (inference/pp_pipeline.py), so a head
    change cannot drift between them. x: (B, S, D) pre-final-norm
    hidden; returns fp32 (B, S, V) logits. Callers own any mesh
    constraint on the result."""
    cdt = cfg.compute_dtype
    x = rms_norm(x, params["final_norm"], cfg.norm_eps).astype(cdt)
    logits = jnp.einsum(
        "bsd,dv->bsv", x, output_weights(cfg, params, cdt),
        preferred_element_type=jnp.float32,
    )
    if cfg.logit_softcap is not None:
        logits = softcap(logits, cfg.logit_softcap)
    return logits


def pattern_period_scan(pattern, x, layer_stack, caches, body_one):
    """Scan whole attn_pattern periods: stacked leaves (L, ...)
    reshape to (L/period, period, ...) and the kinds unroll inside
    the scan body (window sizes are static kernel arguments).
    caches: tuple of (L, ...) arrays riding with the layers;
    body_one(x, lp, cache_slices, kind) -> (x, new_cache_tuple).
    Returns (x, tuple of restacked (L, ...) caches).

    The ONE definition of the period walk, shared by
    forward_with_cache's patterned branch and the pipelined decode's
    per-stage scan (inference/pp_pipeline.py) so the layer order and
    field stacking cannot drift between them."""
    period = len(pattern)

    def greshape(a):
        return a.reshape(a.shape[0] // period, period, *a.shape[1:])

    glp = jax.tree.map(greshape, layer_stack)
    gcaches = tuple(greshape(c) for c in caches)

    def group_body(x, inp):
        gl = inp[0]
        outs = []
        for i, kind in enumerate(pattern):
            lp_i = jax.tree.map(lambda a, i=i: a[i], gl)
            x, nc = body_one(
                x, lp_i, tuple(c[i] for c in inp[1:]), kind
            )
            outs.append(nc)
        stacked = tuple(
            jnp.stack([o[j] for o in outs], axis=0)
            for j in range(len(outs[0]))
        )
        return x, stacked

    x, gnew = jax.lax.scan(group_body, x, (glp,) + gcaches)
    return x, tuple(c.reshape(-1, *c.shape[2:]) for c in gnew)


def forward_with_cache(
    cfg: ModelConfig,
    params: Params,
    tokens: jax.Array,  # (B, S) int32 — new tokens only
    cache,  # KVCache
    *,
    new_tokens_len: Optional[jax.Array] = None,  # (B,) — valid count in `tokens`
    mesh=None,
    fresh_cache: bool = False,
    attn_impl: str = "auto",
):
    """Incremental forward: consumes `tokens` starting at cache.lengths.

    Returns (logits (B, S, V) fp32, updated KVCache). Used for both
    prefill (S = padded prompt length, empty cache, new_tokens_len =
    actual prompt lengths) and decode (S = 1). Writes land at each
    sequence's own length, so ragged batches decode with continuous
    positions and pads never pollute later steps.

    fresh_cache=True (prefill into an all-empty cache) attends within
    the incoming chunk instead of over the max_len buffer — quadratic
    not rectangular, and flash-eligible via attn_impl="auto".
    """
    from shellac_tpu.inference.kvcache import (
        PagedKVCache,
        PatternedKVCache,
        QuantKVCache,
        QuantPagedKVCache,
        QuantPatternedKVCache,
        QuantRollingKVCache,
        RollingKVCache,
    )

    if not cfg.causal:
        raise ValueError(
            "KV-cache generation requires a causal model (cfg.causal=True)"
        )
    paged = isinstance(cache, (PagedKVCache, QuantPagedKVCache))
    quant = isinstance(
        cache, (QuantKVCache, QuantPagedKVCache, QuantRollingKVCache)
    )
    rolled = isinstance(cache, (RollingKVCache, QuantRollingKVCache))
    mixed = isinstance(cache, PatternedKVCache)
    quant_mixed = isinstance(cache, QuantPatternedKVCache)
    if (rolled or mixed or quant_mixed) and cfg.attn_window is None:
        raise ValueError("rolling cache on a model without attn_window")
    if (mixed or quant_mixed) and cfg.attn_pattern is None:
        raise ValueError("patterned cache on a model without attn_pattern")
    cdt = cfg.compute_dtype
    b, s = tokens.shape
    index = cache.lengths  # (B,)
    positions = index[:, None] + jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.int32), (b, s)
    )
    cos, sin = rope_angles(positions, cfg.rope_dim, cfg.rope_theta,
                           yarn=cfg.rope_yarn, llama3=cfg.rope_llama3,
                           linear=cfg.rope_linear)
    if cfg.rope_local_theta is not None:
        cos_l, sin_l = rope_angles(
            positions, cfg.rope_dim, cfg.rope_local_theta
        )
    else:
        cos_l = sin_l = None

    x = _embed_tokens(cfg, params, tokens, cdt, mesh=mesh)
    x = constrain(x, mesh, ("batch", "seq", None))

    tables = cache.tables if paged else None

    def run_block(x, lp, ck, cv, moe_flag, scales=None, attn_kind=None,
                  block_rolled=None):
        local = cos_l is not None and attn_kind == "window"
        return _block(
            cfg, mesh, attn_impl, x, lp,
            cos_l if local else cos, sin_l if local else sin,
            cache=(ck, cv, index, positions), fresh_cache=fresh_cache,
            page_tables=tables, moe_layer=moe_flag, kv_scales=scales,
            attn_kind=attn_kind,
            rolled=rolled if block_rolled is None else block_rolled,
            new_len=new_tokens_len,
        )

    def pattern_scan(x, layer_stack, caches, body_one):
        return pattern_period_scan(
            cfg.attn_pattern, x, layer_stack, caches, body_one
        )

    # Cache leaves riding the layer scans: values only (bf16) or values
    # + scale stacks (int8). ONE set of stack-dispatch bodies serves
    # both, threading the scales to run_block when present — the same
    # field-count parameterization the mixed branch uses. new_ks/new_vs
    # exist only in quant mode (the final replace checks).
    if mixed or quant_mixed:
        cleaves = ()  # mixed caches carry kw/vw/kf/vf, not k/v
    elif quant:
        cleaves = (cache.k, cache.v, cache.ks, cache.vs)
    else:
        cleaves = (cache.k, cache.v)

    def _scales_of(vals):
        return (vals[2], vals[3]) if quant else None

    if first_k_layout(cfg):
        # DeepSeek layout: dense prefix stack, then the all-MoE tail.
        kk = cfg.first_k_dense

        def stack_body(moe_flag):
            def body(x, layer_in):
                lp, vals = layer_in[0], layer_in[1:]
                x, nc, _ = run_block(
                    x, lp, vals[0], vals[1], moe_flag, _scales_of(vals)
                )
                return x, nc

            return body

        x, nd = jax.lax.scan(
            stack_body(False), x,
            (params["layers"]["dense"],) + tuple(a[:kk] for a in cleaves),
        )
        x, nm = jax.lax.scan(
            stack_body(True), x,
            (params["layers"]["moe"],) + tuple(a[kk:] for a in cleaves),
        )
        news = tuple(
            jnp.concatenate([d, m], axis=0) for d, m in zip(nd, nm)
        )
        if quant:
            new_k, new_v, new_ks, new_vs = news
        else:
            new_k, new_v = news
    elif grouped_moe(cfg):
        # Interleaved stacks: scan whole (dense^(every-1), moe) groups.
        every = cfg.moe_every
        ng = cfg.n_layers // every
        gc = tuple(a.reshape(ng, every, *a.shape[1:]) for a in cleaves)

        def group_body(x, inp):
            glp, cg = inp[0], inp[1:]

            def dense_body(x2, li):
                lp, vals = li[0], li[1:]
                x2, nc, _ = run_block(
                    x2, lp, vals[0], vals[1], False, _scales_of(vals)
                )
                return x2, nc

            x, nd = jax.lax.scan(
                dense_body, x,
                (glp["dense"],) + tuple(c[: every - 1] for c in cg),
            )
            moe_vals = tuple(c[every - 1] for c in cg)
            x, nm, _ = run_block(
                x, glp["moe"], moe_vals[0], moe_vals[1], True,
                _scales_of(moe_vals),
            )
            return x, tuple(
                jnp.concatenate([d, m[None]], axis=0)
                for d, m in zip(nd, nm)
            )

        x, gn = jax.lax.scan(group_body, x, (params["layers"],) + gc)
        news = tuple(a.reshape(cfg.n_layers, *a.shape[2:]) for a in gn)
        if quant:
            new_k, new_v, new_ks, new_vs = news
        else:
            new_k, new_v = news
    elif mixed or quant_mixed:
        # Mixed ring/dense stacks: the scan walks pattern periods with
        # per-kind cursors — "window" blocks consume ring rows (rolled
        # update + rolled read), "full" blocks consume dense rows (the
        # Pallas decode kernel path). One body covers bf16 (2 fields
        # per kind) and int8 (4: values + scale stacks, threading the
        # scales to run_block so window blocks take the quantized ring
        # and full blocks the dense int8 path).
        from shellac_tpu.inference.kvcache import pattern_kind_counts

        w_names = (("kw", "vw", "kws", "vws") if quant_mixed
                   else ("kw", "vw"))
        f_names = (("kf", "vf", "kfs", "vfs") if quant_mixed
                   else ("kf", "vf"))
        nfields = len(w_names)
        period = len(cfg.attn_pattern)
        ng = cfg.n_layers // period
        nw, nf = pattern_kind_counts(cfg)
        greshape = lambda a, n: a.reshape(ng, n, *a.shape[1:])  # noqa: E731
        glp = jax.tree.map(
            lambda a: a.reshape(ng, period, *a.shape[1:]),
            params["layers"],
        )
        gw = tuple(greshape(getattr(cache, n), nw) for n in w_names)
        gf = tuple(greshape(getattr(cache, n), nf) for n in f_names)

        def group_body(x, inp):
            gl = inp[0]
            w_in = inp[1:1 + nfields]
            f_in = inp[1 + nfields:]
            w_out, f_out = [], []
            cursors = {"window": 0, "full": 0}
            for i, kind in enumerate(cfg.attn_pattern):
                lp_i = jax.tree.map(lambda a, i=i: a[i], gl)
                is_w = kind == "window"
                src, outs = (w_in, w_out) if is_w else (f_in, f_out)
                cur = cursors[kind]
                scales = ((src[2][cur], src[3][cur]) if nfields == 4
                          else None)
                x, nc, _ = run_block(
                    x, lp_i, src[0][cur], src[1][cur], None, scales,
                    attn_kind=kind, block_rolled=is_w,
                )
                outs.append(nc)
                cursors[kind] = cur + 1
            stack = lambda outs, j: jnp.stack(  # noqa: E731
                [o[j] for o in outs], axis=0
            )
            return x, tuple(
                stack(outs, j)
                for outs in (w_out, f_out) for j in range(nfields)
            )

        x, news = jax.lax.scan(group_body, x, (glp,) + gw + gf)
        backflat = lambda a: a.reshape(-1, *a.shape[2:])  # noqa: E731
        news = [backflat(a) for a in news]
        if quant_mixed:
            (new_kw, new_vw, new_kws, new_vws,
             new_kf, new_vf, new_kfs, new_vfs) = news
        else:
            new_kw, new_vw, new_kf, new_vf = news
    elif cfg.attn_pattern is not None:
        def body_one(x, lp, cs, kind):
            x, nc, _ = run_block(
                x, lp, cs[0], cs[1], None, _scales_of(cs), attn_kind=kind
            )
            return x, nc

        x, news = pattern_scan(x, params["layers"], cleaves, body_one)
        if quant:
            new_k, new_v, new_ks, new_vs = news
        else:
            new_k, new_v = news
    else:
        def scan_body(x, layer_in):
            lp, vals = layer_in[0], layer_in[1:]
            x, new_cache, _ = run_block(
                x, lp, vals[0], vals[1], None, _scales_of(vals)
            )
            return x, new_cache

        x, news = jax.lax.scan(
            scan_body, x, (params["layers"],) + cleaves
        )
        if quant:
            new_k, new_v, new_ks, new_vs = news
        else:
            new_k, new_v = news

    logits = unembed(cfg, params, x)
    if new_tokens_len is None:
        new_lengths = index + s
    else:
        new_lengths = index + new_tokens_len.astype(jnp.int32)
    if quant:
        new_cache = cache.replace(
            k=new_k, v=new_v, ks=new_ks, vs=new_vs, lengths=new_lengths
        )
    elif quant_mixed:
        new_cache = cache.replace(
            kw=new_kw, vw=new_vw, kws=new_kws, vws=new_vws,
            kf=new_kf, vf=new_vf, kfs=new_kfs, vfs=new_vfs,
            lengths=new_lengths,
        )
    elif mixed:
        new_cache = cache.replace(
            kw=new_kw, vw=new_vw, kf=new_kf, vf=new_vf,
            lengths=new_lengths,
        )
    else:
        new_cache = cache.replace(k=new_k, v=new_v, lengths=new_lengths)
    return logits, new_cache


def num_params(params: Params) -> int:
    return sum(x.size for x in jax.tree.leaves(params))
