"""Named model presets."""

from __future__ import annotations

from shellac_tpu.config import MLAConfig, ModelConfig, MoEConfig

# fmt: off
PRESETS = {
    # test-scale configs (CPU-friendly)
    "tiny": ModelConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                        max_seq_len=128, remat=False),
    "tiny-gqa": ModelConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            n_kv_heads=2, max_seq_len=128, remat=False),
    "tiny-moe": ModelConfig(vocab_size=256, d_model=64, n_layers=2, n_heads=4,
                            max_seq_len=128, remat=False,
                            moe=MoEConfig(num_experts=4, num_experts_per_token=2)),
    "tiny-moe-shared": ModelConfig(vocab_size=256, d_model=64, n_layers=2,
                                   n_heads=4, max_seq_len=128, remat=False,
                                   moe=MoEConfig(num_experts=4,
                                                 num_experts_per_token=2,
                                                 num_shared_experts=1)),
    "tiny-moe-interleaved": ModelConfig(vocab_size=256, d_model=64,
                                        n_layers=4, n_heads=4,
                                        max_seq_len=128, remat=False,
                                        moe=MoEConfig(num_experts=4,
                                                      num_experts_per_token=2),
                                        moe_every=2),
    "tiny-encoder": ModelConfig(vocab_size=256, d_model=64, n_layers=2,
                                n_heads=4, max_seq_len=128, remat=False,
                                causal=False),
    # The full GPT-OSS shape in miniature: attention sinks, q/k/v/o
    # biases, alternating sliding/full layers, softmax-after-top-k MoE
    # with biased experts and the clamped (up+1)*glu activation.
    "tiny-gptoss": ModelConfig(vocab_size=256, d_model=64, n_layers=4,
                               n_heads=4, n_kv_heads=2, max_seq_len=128,
                               remat=False, attn_window=16,
                               attn_pattern=("window", "full"),
                               attn_sink=True, attn_bias=True,
                               attn_out_bias=True, tie_embeddings=False,
                               moe=MoEConfig(num_experts=4,
                                             num_experts_per_token=2,
                                             d_ff_expert=96,
                                             scoring="softmax_topk",
                                             expert_bias=True,
                                             gate_limit=7.0,
                                             expert_act="gptoss",
                                             dropless=True)),
    # The full Gemma-3 (text) shape in miniature: 5:1 local/global
    # pattern, dual rope (unscaled local theta / linear-scaled global),
    # qk-norm, sandwich norms, no softcaps.
    "tiny-gemma3": ModelConfig(vocab_size=256, d_model=64, n_layers=6,
                               n_heads=4, n_kv_heads=2, max_seq_len=128,
                               remat=False, attn_window=16,
                               attn_pattern=("window",) * 5 + ("full",),
                               rope_theta=1_000_000.0,
                               rope_local_theta=10_000.0, rope_linear=8.0,
                               attn_scale=16 ** -0.5, qk_norm=True,
                               post_norms=True, activation="geglu",
                               embed_scale=True),
    # The full Gemma-2 shape in miniature: alternating local/global
    # attention, score + final-logit tanh capping, sandwich norms, a
    # query_pre_attn_scalar score scale, GeGLU, scaled embeddings.
    "tiny-gemma2": ModelConfig(vocab_size=256, d_model=64, n_layers=4,
                               n_heads=4, n_kv_heads=2, max_seq_len=128,
                               remat=False, attn_window=16,
                               attn_pattern=("window", "full"),
                               attn_softcap=50.0, logit_softcap=30.0,
                               attn_scale=16 ** -0.5, post_norms=True,
                               activation="geglu", embed_scale=True),
    "tiny-mla": ModelConfig(vocab_size=256, d_model=64, n_layers=2,
                            n_heads=4, max_seq_len=128, remat=False,
                            mla=MLAConfig(kv_lora_rank=32, q_lora_rank=24,
                                          qk_nope_head_dim=16,
                                          qk_rope_head_dim=8, v_head_dim=16)),
    # The full DeepSeek-V2 shape in miniature: MLA + first-k-dense +
    # narrow routed experts + a shared expert, un-normalized scaled
    # top-k routing.
    "tiny-deepseek": ModelConfig(vocab_size=256, d_model=64, n_layers=3,
                                 n_heads=4, max_seq_len=128, remat=False,
                                 mla=MLAConfig(kv_lora_rank=32,
                                               q_lora_rank=24,
                                               qk_nope_head_dim=16,
                                               qk_rope_head_dim=8,
                                               v_head_dim=16),
                                 first_k_dense=1,
                                 moe=MoEConfig(num_experts=4,
                                               num_experts_per_token=2,
                                               d_ff_expert=48,
                                               num_shared_experts=1,
                                               norm_topk_prob=False,
                                               routed_scaling_factor=1.0,
                                               # DeepSeek computes every
                                               # routed token (and only
                                               # dropless MoE keeps the
                                               # serving parity invariant
                                               # under prompt padding).
                                               dropless=True)),
    # DeepSeek-V2-Lite shape, dense-MLP variant (MLA decode cache:
    # 576 per token vs 16*(192+128) = 5120 expanded — an 8.9x shrink).
    "shellac-mla-2b": ModelConfig(vocab_size=32768, d_model=2048,
                                  n_layers=20, n_heads=16,
                                  max_seq_len=4096,
                                  mla=MLAConfig(kv_lora_rank=512,
                                                q_lora_rank=None,
                                                qk_nope_head_dim=128,
                                                qk_rope_head_dim=64,
                                                v_head_dim=128)),
    # single-chip bench scale (v5e: 16 GiB HBM)
    "shellac-270m": ModelConfig(vocab_size=32768, d_model=1024, n_layers=12,
                                n_heads=8, n_kv_heads=8, head_dim=128,
                                max_seq_len=2048),
    "shellac-1b": ModelConfig(vocab_size=32768, d_model=2048, n_layers=16,
                              n_heads=16, n_kv_heads=8, head_dim=128,
                              max_seq_len=2048),
    # multi-chip flagship shape (sharded over a mesh)
    "shellac-7b": ModelConfig(vocab_size=32768, d_model=4096, n_layers=32,
                              n_heads=32, n_kv_heads=8, head_dim=128,
                              max_seq_len=4096),
}
# fmt: on


def get_model_config(name: str) -> ModelConfig:
    if name not in PRESETS:
        raise KeyError(f"unknown model {name!r}; have {sorted(PRESETS)}")
    return PRESETS[name]
