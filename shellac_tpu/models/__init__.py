from shellac_tpu.models import transformer
from shellac_tpu.models.registry import PRESETS, get_model_config

__all__ = ["transformer", "PRESETS", "get_model_config"]
