"""Command-line interface: `python -m shellac_tpu <command>`.

Commands:
  train     train a preset (or JSON-configured) model on token shards or
            synthetic data, with checkpoints/resume and metrics logging
  eval      token-weighted NLL / perplexity of a checkpoint over shards
  generate  autoregressive sampling from a checkpoint (or random init),
            optionally speculative with a smaller draft preset
  info      show presets, a config's derived dims, and parameter counts
  top       live fleet dashboard over a serving tier URL (per-replica
            load, SLO burn rates, step-phase attribution; --once for
            scripts, --trace <id> for one request's timeline)
  lint      JAX/TPU-aware static analysis of the source tree (the SH
            rule set; see docs/static_analysis.md)

Token ids go in and out as comma-separated integers; plug a tokenizer in
front as needed. Everything here is a thin shell over the library — each
command body is the same code a user would write in a script.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import sys

import numpy as np


def _decode_ticks_arg(v: str):
    """--decode-ticks parser: an int >= 1, or 'auto' (startup sweep)."""
    if v == "auto":
        return v
    try:
        return int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--decode-ticks wants an integer or 'auto', got {v!r}"
        )


def _prefill_chunk_arg(v: str):
    """--prefill-chunk parser: an int >= 1, or 'auto' (startup sweep
    of chunk candidates on the live engine — the TTFT-vs-TPOT
    fairness knob, measured instead of guessed)."""
    if v == "auto":
        return v
    try:
        return int(v)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"--prefill-chunk wants an integer or 'auto', got {v!r}"
        )


def _model_config(args):
    from shellac_tpu.config import ModelConfig
    from shellac_tpu.models.registry import PRESETS

    if getattr(args, "config", None):
        with open(args.config) as f:
            raw = json.load(f)
        base = PRESETS[raw.pop("preset")] if "preset" in raw else ModelConfig()
        return base.replace(**raw).validate()
    return PRESETS[args.model].validate()


def _parallel_config(spec: str):
    from shellac_tpu.config import ParallelConfig

    if not spec:
        return None
    kw = {}
    for part in spec.split(","):
        k, v = part.split("=")
        kw[k.strip()] = int(v)
    return ParallelConfig(**kw)


def _mesh_from(args):
    pcfg = _parallel_config(getattr(args, "mesh", "") or "")
    if pcfg is None:
        return None
    from shellac_tpu.parallel.mesh import make_mesh

    return make_mesh(pcfg)


def _data_iter(args, cfg, batch_size, seq_len, num_batches=None, skip=0):
    from shellac_tpu.training.data import shard_batches, token_batches

    if args.data:
        return shard_batches(
            args.data, batch_size=batch_size, seq_len=seq_len,
            seed=args.seed, num_batches=num_batches, skip=skip,
        )
    # Synthetic corpus: a noisy periodic token stream, so the loss has
    # structure to fall on (unlike uniform random tokens).
    rng = np.random.default_rng(args.seed)
    n = max(seq_len * 64, 1 << 16)
    base = np.arange(n, dtype=np.int32) % min(97, cfg.vocab_size)
    noise = rng.integers(0, cfg.vocab_size, size=n)
    corpus = np.where(rng.random(n) < 0.1, noise, base).astype(np.int32)
    return token_batches(
        corpus, batch_size=batch_size, seq_len=seq_len, seed=args.seed,
        num_batches=num_batches, skip=skip,
    )


def _load_native(native_dir):
    """(cfg, params) from a directory written by `convert`."""
    import os

    import orbax.checkpoint as ocp

    from shellac_tpu.config import (
        Llama3RopeConfig,
        MLAConfig,
        ModelConfig,
        MoEConfig,
        YarnConfig,
    )

    with open(os.path.join(native_dir, "config.json")) as f:
        cfg_d = json.load(f)
    # Rehydrate every nested config dataclass (dataclasses.asdict wrote
    # them as plain dicts).
    nested = {
        "moe": MoEConfig, "mla": MLAConfig,
        "rope_yarn": YarnConfig, "rope_llama3": Llama3RopeConfig,
    }
    kw = {}
    for name, cls in nested.items():
        d = cfg_d.pop(name, None)
        kw[name] = cls(**d) if d else None
    cfg = ModelConfig(**cfg_d, **kw)
    params = ocp.StandardCheckpointer().restore(
        os.path.join(os.path.abspath(native_dir), "params")
    )
    return cfg.validate(), params


def _restore_params(args, cfg, train_cfg=None):
    """Params from --ckpt-dir (latest step), or a fresh random init.

    With --ema (eval/generate on a checkpoint trained with
    TrainConfig.ema_decay), returns the averaged weights instead."""
    import jax

    from shellac_tpu.models import transformer

    use_ema = bool(getattr(args, "ema", False))
    if getattr(args, "ckpt_dir", None):
        from shellac_tpu.config import TrainConfig
        from shellac_tpu.training.checkpoint import Checkpointer
        from shellac_tpu.training.trainer import init_train_state

        tcfg = train_cfg or TrainConfig(
            # Any non-None decay makes the abstract state carry
            # ema_params so the restore's structure matches a
            # checkpoint that has them.
            ema_decay=0.999 if use_ema else None,
        )
        ckpt = Checkpointer(args.ckpt_dir)
        abstract = jax.eval_shape(
            lambda: init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        )
        state = ckpt.restore(abstract_state=abstract)
        if use_ema:
            if state.ema_params is None:
                raise SystemExit(
                    "--ema: checkpoint has no EMA parameters (train with "
                    "TrainConfig.ema_decay)"
                )
            return state.ema_params
        return state.params
    return transformer.init_params(cfg, jax.random.PRNGKey(args.seed))


def _resume_skip(args) -> int:
    """Batches already consumed by a checkpointed run: resume continues
    the data stream where it left off rather than replaying (and
    re-training on) the earliest batches. A cheap directory scan — the
    real Checkpointer (sweeps, manager threads) is built once, inside
    the loop, which also re-derives this skip via data_factory if the
    restore lands on an older intact step."""
    if not getattr(args, "ckpt_dir", None):
        return 0
    from shellac_tpu.training.checkpoint import latest_step_on_disk

    latest = latest_step_on_disk(args.ckpt_dir)
    return int(latest) if latest is not None else 0


def _train_config(args):
    from shellac_tpu.config import TrainConfig

    kw = {}
    for field in ("learning_rate", "warmup_steps", "weight_decay",
                  "grad_accum", "seed", "optimizer", "quant",
                  "ema_decay"):
        v = getattr(args, field, None)
        if v is not None:
            kw[field] = v
    kw["total_steps"] = args.steps
    return TrainConfig(**kw)


def cmd_train(args):
    from shellac_tpu.training.loop import fit

    cfg = _model_config(args)
    tcfg = _train_config(args)

    from shellac_tpu.parallel.distributed import initialize

    multihost = initialize()
    if multihost:
        import jax

        from shellac_tpu.parallel.distributed import global_mesh

        if not args.mesh:
            raise SystemExit(
                "multi-host train needs an explicit --mesh multiplying "
                "out to the GLOBAL device count (e.g. fsdp=32)"
            )
        if args.lora_rank is not None:
            raise SystemExit("--lora-rank training is single-host")
        pcfg = _parallel_config(args.mesh)
        mesh = global_mesh(pcfg)
        nbatch = pcfg.dp * pcfg.fsdp
        nproc = jax.process_count()
        if nbatch > 1:
            # The batch axes span processes: --batch is the GLOBAL batch
            # size; each process loads its share from a distinct stream.
            # The shards must align with process boundaries, or two
            # processes would contribute DIFFERENT rows to the same
            # shard region (undefined data, or a rejected local shape).
            if nbatch % nproc:
                raise SystemExit(
                    f"dp*fsdp={nbatch} must be a multiple of the "
                    f"{nproc} processes (batch shards must align with "
                    "process boundaries); use dp/fsdp >= processes or "
                    "a tp/pp-only mesh"
                )
            if args.batch % nproc:
                raise SystemExit(
                    f"--batch {args.batch} must divide evenly over "
                    f"{nproc} processes"
                )
            args.batch //= nproc
            args.seed = args.seed + jax.process_index()
        # else (tp/pp-only mesh): the batch is replicated across
        # processes — every process must feed IDENTICAL data, so the
        # seed stays shared.
    else:
        mesh = _mesh_from(args)
    if args.lora_rank is not None:
        data = _data_iter(args, cfg, args.batch, args.seq,
                          skip=_resume_skip(args))
        rc = _train_lora(args, cfg, tcfg, mesh, data)
        _dump_metrics(args)
        return rc

    def data_factory(step):
        # fit builds the stream from this exactly once, at the step the
        # run actually starts from (resume restore included), and
        # sentinel rollbacks re-derive it from the restored step: the
        # deterministic skip path replays exactly the batches the
        # rolled-back steps consumed, so a recovered run finishes
        # identical to an unfaulted one.
        return _data_iter(args, cfg, args.batch, args.seq, skip=step)

    state = fit(
        cfg, tcfg, None,
        mesh=mesh,
        checkpoint_dir=args.ckpt_dir,
        checkpoint_every=args.ckpt_every,
        log_path=args.log_path,
        log_every=args.log_every,
        heartbeat_path=args.heartbeat_file,
        anomaly_action=args.anomaly_action,
        max_restores=args.max_restores,
        data_factory=data_factory,
    )
    _dump_metrics(args)
    import jax

    print(json.dumps({"final_step": int(jax.device_get(state.step))}))
    return 0


def _dump_metrics(args):
    """train --metrics-file: write the shared registry's snapshot (the
    shellac_train_* gauges and the step-interval histogram the loop
    deposited) as JSON, so a run's final throughput picture lands next
    to its JSONL log in one scrape-equivalent file."""
    path = getattr(args, "metrics_file", None)
    if not path:
        return
    from shellac_tpu.obs import get_registry

    with open(path, "w") as f:
        json.dump(get_registry().snapshot(), f, indent=2)
        f.write("\n")


def _train_lora(args, cfg, tcfg, mesh, data):
    """train --lora-rank: adapter-only fine-tuning over a frozen base.

    Base weights come from --base-ckpt (a regular train checkpoint) or
    a seeded random init; --ckpt-dir holds ONLY the (tiny) adapter
    state plus a lora_config.json that eval/generate --lora-dir read
    back, so the adapter checkpoint is self-describing.
    """
    import os

    import jax

    from shellac_tpu.training.loop import fit_lora
    from shellac_tpu.training.lora import LoRAConfig

    for knob in ("grad_accum", "quant", "ema_decay"):
        if getattr(args, knob, None):
            raise SystemExit(
                f"--lora-rank does not support --{knob.replace('_', '-')} "
                "(the adapter train step has no accumulation/quant/EMA)"
            )
    lcfg = LoRAConfig(
        rank=args.lora_rank,
        alpha=args.lora_alpha,
        targets=tuple(t.strip() for t in args.lora_targets.split(",")),
    ).validate(cfg)
    base_params = _restore_base_params(args, cfg, mesh)
    if args.ckpt_dir:
        os.makedirs(args.ckpt_dir, exist_ok=True)
        meta = {
            "rank": lcfg.rank,
            "alpha": lcfg.alpha,
            "targets": list(lcfg.targets),
            "optimizer": tcfg.optimizer,
            "mu_dtype": tcfg.mu_dtype,
        }
        meta_path = os.path.join(args.ckpt_dir, "lora_config.json")
        if os.path.exists(meta_path):
            # Resuming: the flags must match the checkpoint — silently
            # rewriting the metadata would brick a valid adapter dir
            # the moment the restore failed on structure mismatch.
            with open(meta_path) as f:
                saved = json.load(f)
            if saved != meta:
                raise SystemExit(
                    f"--ckpt-dir {args.ckpt_dir} holds adapters trained "
                    f"with {saved}; current flags give {meta}. Match the "
                    "original --lora-* / --optimizer flags or use a "
                    "fresh --ckpt-dir."
                )
        else:
            with open(meta_path, "w") as f:
                json.dump(meta, f)
    state = fit_lora(
        cfg, tcfg, lcfg, base_params, data,
        mesh=mesh,
        checkpoint_dir=args.ckpt_dir,
        checkpoint_every=args.ckpt_every,
        log_path=args.log_path,
        log_every=args.log_every,
    )
    print(json.dumps({
        "final_step": int(jax.device_get(state.step)),
        "lora_rank": lcfg.rank,
        "adapter_params": int(sum(
            x.size for x in jax.tree.leaves(state.lora)
        )),
    }))
    return 0


def _restore_base_params(args, cfg, mesh):
    """Frozen base weights for adapter training: sharded restore when a
    mesh is given (materializing a large base unsharded would OOM), a
    seeded random init otherwise."""
    import jax

    from shellac_tpu.models import transformer

    if not args.base_ckpt:
        params = transformer.init_params(cfg, jax.random.PRNGKey(args.seed))
        if mesh is not None:
            from shellac_tpu.parallel.sharding import shard_pytree

            params = shard_pytree(
                params, mesh, transformer.logical_axes(cfg)
            )
        return params
    if mesh is None:
        return _restore_params(
            argparse.Namespace(ckpt_dir=args.base_ckpt, ema=False,
                               seed=args.seed), cfg,
        )
    from shellac_tpu.config import TrainConfig
    from shellac_tpu.training.checkpoint import Checkpointer
    from shellac_tpu.training.trainer import init_train_state

    abstract = jax.eval_shape(
        lambda: init_train_state(cfg, TrainConfig(), jax.random.PRNGKey(0))
    )
    state = Checkpointer(args.base_ckpt).restore(
        abstract_state=abstract, mesh=mesh, model_cfg=cfg
    )
    return state.params


def _apply_lora(args, cfg, params):
    """Merge adapters from --lora-dir (written by train --lora-rank)
    into base params; no-op without the flag."""
    if not getattr(args, "lora_dir", None):
        return params
    import os

    import jax

    from shellac_tpu.config import TrainConfig
    from shellac_tpu.training.checkpoint import Checkpointer
    from shellac_tpu.training.lora import (
        LoRAConfig,
        init_lora_state,
        merge_lora,
    )

    with open(os.path.join(args.lora_dir, "lora_config.json")) as f:
        d = json.load(f)
    lcfg = LoRAConfig(rank=d["rank"], alpha=d["alpha"],
                      targets=tuple(d["targets"]))
    # Only optimizer/mu_dtype shape the state structure for restore.
    tcfg = TrainConfig(optimizer=d["optimizer"], mu_dtype=d["mu_dtype"])
    abstract = jax.eval_shape(
        lambda: init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(0))
    )
    state = Checkpointer(args.lora_dir).restore(abstract_state=abstract)
    # Adapters trained on a mesh restore with their saved sharding;
    # the eager merge below must not mix committed placements with the
    # host-restored base, so pull the (tiny) adapters to host first.
    return merge_lora(params, jax.device_get(state.lora), lcfg)


def cmd_dpo(args):
    """Preference fine-tuning (DPO) from a JSONL of pairs.

    The policy starts from --base-ckpt (or random); the frozen
    reference defaults to a copy of the starting policy. Data rows:
    {"prompt": ..., "chosen": ..., "rejected": ...} with token-id
    lists, or strings when --tokenizer is given.
    """
    from shellac_tpu.training.dpo import (
        DPOConfig,
        fit_dpo,
        preference_batches,
    )

    cfg = _model_config(args)
    tcfg = _train_config(args)
    dcfg = DPOConfig(
        beta=args.beta,
        loss_type=args.loss_type,
        label_smoothing=args.label_smoothing,
        reference_free=args.reference_free,
    ).validate()
    mesh = _mesh_from(args)
    tokenizer = None
    if args.tokenizer:
        from shellac_tpu.training.tokenizer import ByteTokenizer

        tokenizer = ByteTokenizer()
    data = preference_batches(
        args.data, args.batch, args.max_len,
        tokenizer=tokenizer, seed=args.seed, skip=_resume_skip(args),
    )
    init_params = _restore_base_params(args, cfg, mesh)
    state = fit_dpo(
        cfg, tcfg, dcfg, data,
        init_params=init_params,
        mesh=mesh,
        checkpoint_dir=args.ckpt_dir,
        checkpoint_every=args.ckpt_every,
        log_path=args.log_path,
        log_every=args.log_every,
    )
    import jax

    print(json.dumps({"final_step": int(jax.device_get(state.step))}))
    return 0


def cmd_distill(args):
    """Distill a frozen teacher checkpoint into a (usually smaller)
    student. The teacher is any checkpoint this framework can run; only
    the vocabularies must match."""
    import jax

    from shellac_tpu.training.distill import (
        DistillConfig,
        fit_distill,
    )

    cfg = _model_config(args)
    tcfg = _train_config(args)
    dcfg = DistillConfig(
        temperature=args.kd_temperature, alpha=args.alpha, kind=args.kind,
    ).validate()
    mesh = _mesh_from(args)
    if args.teacher_model:
        from shellac_tpu.models.registry import get_model_config

        teacher_cfg = get_model_config(args.teacher_model)
    else:
        teacher_cfg = cfg
    teacher_params = _restore_base_params(
        argparse.Namespace(base_ckpt=args.teacher_ckpt, seed=args.seed),
        teacher_cfg, mesh,
    )
    data = _data_iter(args, cfg, args.batch, args.seq,
                      skip=_resume_skip(args))
    state = fit_distill(
        cfg, tcfg, dcfg, teacher_params, data,
        teacher_cfg=teacher_cfg, mesh=mesh,
        checkpoint_dir=args.ckpt_dir, checkpoint_every=args.ckpt_every,
        log_path=args.log_path, log_every=args.log_every,
    )
    print(json.dumps({"final_step": int(jax.device_get(state.step))}))
    return 0


def cmd_eval(args):
    from shellac_tpu.training.evaluate import evaluate

    cfg = _model_config(args)
    params = _apply_lora(args, cfg, _restore_params(args, cfg))
    data = _data_iter(args, cfg, args.batch, args.seq,
                      num_batches=args.batches)
    out = evaluate(cfg, params, data, max_batches=args.batches)
    print(json.dumps({k: round(v, 4) if isinstance(v, float) else v
                      for k, v in out.items()}))
    return 0


def cmd_tokenize(args):
    from shellac_tpu.training.data import write_token_shard
    from shellac_tpu.training.tokenizer import BPETokenizer, get_tokenizer

    if args.train_bpe is not None:
        if not args.tokenizer.endswith(".json"):
            raise SystemExit(
                "--train-bpe writes a .json tokenizer file; point "
                "--tokenizer at the output path (e.g. tok.json)"
            )
        tok = BPETokenizer.train(
            args.input, vocab_size=args.train_bpe, out_path=args.tokenizer
        )
    else:
        tok = get_tokenizer(args.tokenizer)
    docs = []
    for path in args.input:
        with open(path, encoding="utf-8") as f:
            docs.append(f.read())
    tokens = tok.encode_documents(docs)
    write_token_shard(args.output, tokens)
    print(json.dumps({
        "output": args.output,
        "tokens": int(tokens.size),
        "vocab_size": tok.vocab_size,
    }))
    return 0


def cmd_generate(args):
    import jax.numpy as jnp

    if getattr(args, "native_dir", None):
        cfg, params = _load_native(args.native_dir)
    else:
        cfg = _model_config(args)
        params = _restore_params(args, cfg)
    params = _apply_lora(args, cfg, params)
    tok = None
    if args.text is not None:
        from shellac_tpu.training.tokenizer import get_tokenizer

        tok = get_tokenizer(args.tokenizer)
        ids = tok.encode(args.text, bos=False)
        prompt = ids[None, :].astype(np.int32)
    else:
        if args.prompt is None:
            raise SystemExit("need --prompt or --text")
        prompt = np.array([[int(t) for t in args.prompt.split(",")]], np.int32)
    if prompt.size == 0:
        raise SystemExit("empty prompt")

    stop_seqs = []
    if args.stop:
        for part in args.stop.split(";"):
            if not part:
                continue
            try:
                seq = [int(t) for t in part.split(",")]
            except ValueError:
                raise SystemExit(
                    f'--stop: bad token-id sequence {part!r} '
                    '(expected e.g. "13,10;0")'
                )
            if not seq:
                raise SystemExit("--stop: empty stop sequence")
            stop_seqs.append(seq)
    if args.stop_text:
        if tok is None:
            from shellac_tpu.training.tokenizer import get_tokenizer

            tok = get_tokenizer(args.tokenizer)
        for s in args.stop_text:
            seq = list(map(int, tok.encode(s, bos=False)))
            if not seq:
                raise SystemExit(
                    f"--stop-text: {s!r} encodes to zero tokens"
                )
            stop_seqs.append(seq)

    def apply_stop(ids):
        if not stop_seqs:
            return ids
        from shellac_tpu.inference.engine import truncate_at_stop

        return np.asarray(truncate_at_stop(ids[None], stop_seqs)[0], np.int64)

    if args.draft_model:
        if args.kv_quant:
            raise SystemExit("--kv-quant does not compose with "
                             "--draft-model")
        if args.num_beams and args.num_beams > 1:
            raise SystemExit("--num-beams does not compose with "
                             "--draft-model (beam search is "
                             "deterministic; speculative decoding "
                             "samples)")
        from shellac_tpu.inference.speculative import SpeculativeEngine
        from shellac_tpu.models.registry import PRESETS

        dcfg = PRESETS[args.draft_model]
        import jax

        from shellac_tpu.models import transformer

        dparams = transformer.init_params(dcfg, jax.random.PRNGKey(args.seed))
        eng = SpeculativeEngine(
            cfg, params, dcfg, dparams,
            gamma=args.gamma, temperature=args.temperature,
        )
        out = eng.generate(jnp.asarray(prompt), max_new_tokens=args.max_new)
        ids = apply_stop(np.asarray(out.tokens)[0])
        result = {
            "tokens": ids.tolist(),
            "accept_rate": round(float(out.accept_rate), 4),
            "rounds": int(out.rounds),
        }
        if tok is not None:
            result["text"] = tok.decode(ids)
        print(json.dumps(result))
        return 0

    from shellac_tpu.inference.engine import Engine

    if args.quantize:
        from shellac_tpu.ops.quant import quantize_params

        params = quantize_params(cfg, params)
    eng = Engine(
        cfg, params,
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        kv_quant=args.kv_quant,
    )
    constraint = None
    if getattr(args, "json_schema", None):
        # Schema-constrained beams: compile through the same
        # schema->regex->token-DFA path the server uses, so the CLI
        # surface and HTTP surface cannot drift (docs/
        # structured_output.md).
        if not args.num_beams or args.num_beams < 1:
            raise SystemExit("--json-schema needs --num-beams >= 1 "
                             "(constrained beam search)")
        if args.eos_id is None:
            raise SystemExit("--json-schema needs --eos-id (the DFA's "
                             "EOS column and beam termination must "
                             "agree)")
        if args.stop_text:
            # The HTTP surface refuses stop with num_beams for the
            # same reason: truncating a schema-constrained beam can
            # leave schema-INVALID output, contradicting the flag's
            # promise.
            raise SystemExit("--stop-text does not compose with "
                             "--json-schema (truncation could break "
                             "the schema)")
        raw = args.json_schema
        if raw.startswith("@"):
            with open(raw[1:]) as f:
                raw = f.read()
        try:
            schema = json.loads(raw)
        except ValueError as e:
            raise SystemExit(f"--json-schema is not valid JSON: {e}")
        if tok is None:
            from shellac_tpu.training.tokenizer import get_tokenizer

            tok = get_tokenizer(args.tokenizer)
        from shellac_tpu.inference.constraints import (
            compile_token_dfa,
            constraint_pattern,
        )

        try:
            constraint = compile_token_dfa(
                constraint_pattern({"json_schema": schema}), tok,
                cfg.vocab_size, args.eos_id,
            )
        except ValueError as e:
            raise SystemExit(f"--json-schema: {e}")
    if args.num_beams and (args.num_beams > 1 or constraint is not None):
        try:
            seqs, scores = eng.beam_search(
                jnp.asarray(prompt)[0], num_beams=args.num_beams,
                max_new_tokens=args.max_new, eos_id=args.eos_id,
                length_penalty=args.length_penalty,
                constraint=constraint,
            )
        except ValueError as e:
            raise SystemExit(f"beam search: {e}")
        if not seqs:
            raise SystemExit("constrained beam search returned no "
                             "valid beams (max-new too small for the "
                             "schema?)")
        ids = np.asarray(apply_stop(np.asarray(seqs[0], np.int64)))
        result = {
            "tokens": ids.tolist(),
            "beam_scores": [round(s, 4) for s in scores],
        }
        if tok is not None:
            result["text"] = tok.decode(ids)
        print(json.dumps(result))
        return 0
    out = eng.generate(jnp.asarray(prompt), max_new_tokens=args.max_new)
    ids = apply_stop(np.asarray(out.tokens)[0])
    result = {"tokens": ids.tolist()}
    if tok is not None:
        result["text"] = tok.decode(ids)
    print(json.dumps(result))
    return 0


def cmd_batch(args):
    """Offline batch generation: JSONL prompts in, JSONL completions
    out, through the continuous-batching engine (slots stay saturated
    across requests — the high-throughput path, no HTTP in the way)."""
    from shellac_tpu.inference.cache import engine_class, resolve_backend_name
    from shellac_tpu.training.tokenizer import get_tokenizer

    try:
        backend_name = resolve_backend_name(
            args.cache_backend, kv_quant=args.kv_quant,
            rolling_window=args.rolling_window,
        )
    except ValueError as e:
        raise SystemExit(str(e))
    cfg = _model_config(args)
    params = _apply_lora(args, cfg, _restore_params(args, cfg))
    mesh = _mesh_from(args)
    if mesh is not None:
        from shellac_tpu.inference.engine import shard_params

        params = shard_params(cfg, params, mesh)
    tok = get_tokenizer(args.tokenizer)
    eng = engine_class(backend_name)(
        cfg, params, n_slots=args.slots,
        max_len=args.max_len or cfg.max_seq_len,
        temperature=args.temperature, eos_id=args.eos_id,
        decode_ticks=args.decode_ticks,
        overlap_decode=args.overlap_decode,
        overlap_prefill=args.overlap_prefill,
        mesh=mesh, seed=args.seed,
        cache_backend=backend_name,
        logprobs=args.logprobs,
    )
    if args.decode_ticks == "auto":
        from shellac_tpu.inference.autotune import maybe_autotune

        maybe_autotune(eng, log=lambda m: print(m, file=sys.stderr))

    rows = []
    with open(args.input) as f:
        for line in f:
            line = line.strip()
            if line:
                rows.append(json.loads(line))
    if not rows:
        raise SystemExit(f"no prompts in {args.input}")

    per_req = ("max_tokens", "temperature", "top_k", "top_p", "min_p",
               "seed", "presence_penalty", "frequency_penalty")
    for i, row in enumerate(rows):
        prompt = row.get("prompt")
        if isinstance(prompt, str):
            ids = tok.encode(prompt)
        elif isinstance(prompt, list):
            ids = np.asarray(prompt, np.int32)
        else:
            raise SystemExit(f"row {i}: prompt must be text or id list")
        kw = {k: row[k] for k in per_req if row.get(k) is not None}
        max_new = int(kw.pop("max_tokens", args.max_new))
        stop = row.get("stop")
        if stop is not None:
            if isinstance(stop, str):
                # OpenAI scalar form: ONE sequence, not per-character.
                stop = [stop]
            try:
                stop = [list(map(int, tok.encode(s)))
                        if isinstance(s, str) else list(map(int, s))
                        for s in stop]
            except TypeError:
                raise SystemExit(
                    f"row {i}: stop must be a string or a list of "
                    "strings / token-id lists"
                )
        try:
            eng.submit(i, ids, max_new, stop=stop, **kw)
        except ValueError as e:
            # One malformed row must fail the job BEFORE any compute,
            # with the row named — not a traceback after checkpoint
            # load and half a batch of generation.
            raise SystemExit(f"row {i}: {e}")

    results = dict(eng.run())

    with open(args.output, "w") as f:
        for i in range(len(rows)):
            out = results[i]
            rec = {"index": i, "tokens": out, "text": tok.decode(out)}
            if args.logprobs:
                lps = eng.finished_logprobs.pop(i, None)
                if lps is not None:
                    rec["logprobs"] = lps
            f.write(json.dumps(rec) + "\n")
    print(json.dumps({
        "output": args.output,
        "requests": len(rows),
        "tokens_generated": int(eng.stats["tokens_generated"]),
        "engine_steps": int(eng.stats["engine_steps"]),
    }))
    return 0


def cmd_serve(args):
    from shellac_tpu.inference.server import serve
    from shellac_tpu.training.tokenizer import get_tokenizer

    if not args.metrics:
        # One switch for the whole process: engines, the server, and
        # the request spans all deposit into the global registry, so
        # disabling it here no-ops every write and /metrics answers
        # 404. Metrics stay ON by default — the cost when nothing
        # scrapes is a few host-side adds per engine STEP.
        from shellac_tpu.obs import get_registry

        get_registry().disable()
    # One resolution path for storage policy: the explicit
    # --cache-backend name and the deprecated legacy aliases (--paged,
    # --kv-quant, --rolling-window) all land on the same backend
    # registry the engines use.
    from shellac_tpu.inference.cache import (
        backend_flags,
        resolve_backend_name,
    )

    try:
        backend_name = resolve_backend_name(
            args.cache_backend, paged=args.paged, kv_quant=args.kv_quant,
            rolling_window=args.rolling_window,
        )
    except ValueError as e:
        raise SystemExit(str(e))
    paged, kvq, rolling = backend_flags(backend_name)
    if args.prefix_cache and not paged:
        raise SystemExit("--prefix-cache requires a paged cache backend "
                         "(--cache-backend paged|paged-int8)")
    if args.draft_model and rolling:
        raise SystemExit(
            "--draft-model (speculative) does not compose with rolling "
            "backends: the verify round re-reads positions a ring may "
            "have already evicted mid-round"
        )
    if args.draft_model and args.decode_ticks not in (1, "auto"):
        raise SystemExit("--draft-model already emits up to gamma+1 tokens "
                         "per step; --decode-ticks must stay 1")
    if args.overlap_decode is None:
        # Default: overlap on — except speculative serving, where the
        # verify round's acceptance counts gate the next round, so a
        # draft-model serve silently keeps strict ordering instead of
        # refusing a previously working invocation.
        args.overlap_decode = not args.draft_model
    elif args.draft_model and args.overlap_decode:
        raise SystemExit(
            "--overlap-decode does not compose with --draft-model (the "
            "verify round's acceptance counts gate the next round); use "
            "--no-overlap-decode"
        )
    if args.overlap_prefill is None:
        # Same default policy as --overlap-decode: on, silently off
        # for speculative serving (draft + target caches fill in
        # lockstep at admission — nothing to defer).
        args.overlap_prefill = not args.draft_model
    elif args.draft_model and args.overlap_prefill:
        raise SystemExit(
            "--overlap-prefill does not compose with --draft-model "
            "(admission fills the draft and target caches in "
            "lockstep); use --no-overlap-prefill"
        )
    if args.draft_model and args.prefill_chunk == "auto":
        raise SystemExit(
            "--prefill-chunk auto does not tune speculative engines "
            "(they pin their own prefill discipline); pass an "
            "explicit chunk size"
        )
    if args.pp_pipeline and (paged or args.draft_model):
        raise SystemExit(
            "--pp-pipeline composes with the slot caches (dense, "
            "dense-int8, rolling backends) only — no paged backends or "
            "--draft-model"
        )
    if args.pp_pipeline and not args.mesh:
        raise SystemExit("--pp-pipeline needs --mesh with pp>=2")

    from shellac_tpu.parallel.distributed import initialize

    multihost = initialize()  # joins the cluster iff the env asks
    if multihost and not args.mesh:
        raise SystemExit(
            "multi-host serve needs an explicit --mesh (e.g. tp=8) "
            "multiplying out to the GLOBAL device count"
        )
    cfg = _model_config(args)
    params = _apply_lora(args, cfg, _restore_params(args, cfg))
    if args.quantize:
        from shellac_tpu.ops.quant import quantize_params

        params = quantize_params(cfg, params)
    mesh = None
    if args.mesh:
        from shellac_tpu.inference.engine import shard_params
        from shellac_tpu.parallel.distributed import global_mesh

        pcfg = _parallel_config(args.mesh)
        if pcfg.sp > 1:
            raise SystemExit(
                "serve --mesh supports tp/pp (and single-host dp/fsdp); "
                "the sequence axis is training-side"
            )
        if multihost and pcfg.pp > 1:
            raise SystemExit(
                "multi-host serve shards with tp only; pp stages would "
                "span hosts and put per-stage cache rows off-host"
            )
        if args.pp_pipeline and pcfg.pp < 2:
            raise SystemExit(
                "--pp-pipeline needs a pp axis in --mesh (e.g. "
                "pp=2,tp=2); got " + args.mesh
            )
        if multihost and (pcfg.dp > 1 or pcfg.fsdp > 1):
            # dp/fsdp shard the KV cache's slot axis; across hosts that
            # puts decode outputs on non-addressable devices and breaks
            # the engine's replicated-host-state contract.
            raise SystemExit(
                "multi-host serve shards with tp only (e.g. --mesh "
                "tp=8); dp/fsdp would split the slot batch across hosts"
            )
        mesh = global_mesh(pcfg)
        params = shard_params(cfg, params, mesh)
    # Engine construction is wrapped in a zero-arg closure wherever an
    # engine is built here: the serving supervisor's auto-recovery
    # (serve --restart-budget) rebuilds a fresh engine from it after a
    # wedge, so the factory must capture everything construction needs.
    engine = None
    engine_factory = None
    from shellac_tpu.inference.cache import engine_class

    # Paged policy knobs travel with the backend name wherever a paged
    # engine (speculative or not) is constructed below.
    paged_extra = {}
    if paged:
        # block_size=None lets the engine resolve the backend's own
        # default (the 32-aligned 64 for int8 pools, 16 for bf16) —
        # ONE source of truth for page geometry.
        paged_extra = {
            "prefix_cache": args.prefix_cache,
            "block_size": args.block_size,
        }
    if args.draft_model:
        import jax

        from shellac_tpu.models import transformer
        from shellac_tpu.models.registry import PRESETS

        kind = engine_class(backend_name, speculative=True)
        dcfg = PRESETS[args.draft_model]
        dparams = transformer.init_params(dcfg, jax.random.PRNGKey(args.seed))
        if mesh is not None:
            dparams = shard_params(dcfg, dparams, mesh)

        def engine_factory():
            return kind(
                cfg, params, dcfg, dparams, gamma=args.gamma,
                n_slots=args.slots, max_len=args.max_len or cfg.max_seq_len,
                temperature=args.temperature, eos_id=args.eos_id,
                seed=args.seed, logprobs=args.logprobs,
                top_logprobs=args.top_logprobs,
                max_prefills_per_step=args.max_prefills_per_step,
                prefill_chunk=args.prefill_chunk,
                mesh=mesh,
                cache_backend=backend_name,
                **paged_extra,
            )

        engine = engine_factory()
    if engine is None and (paged or mesh is not None):
        kind = engine_class(backend_name)
        extra = dict(paged_extra)
        if not paged:
            extra["pp_pipeline"] = args.pp_pipeline

        def engine_factory():
            return kind(
                cfg, params, n_slots=args.slots,
                max_len=args.max_len or cfg.max_seq_len,
                temperature=args.temperature, eos_id=args.eos_id,
                decode_ticks=args.decode_ticks,
                overlap_decode=args.overlap_decode,
                overlap_prefill=args.overlap_prefill,
                max_prefills_per_step=args.max_prefills_per_step,
                prefill_chunk=args.prefill_chunk,
                logprobs=args.logprobs,
                top_logprobs=args.top_logprobs,
                mesh=mesh,
                cache_backend=backend_name,
                **extra,
            )

        engine = engine_factory()
    if multihost:
        from shellac_tpu.inference.multihost import MultihostEngine

        engine = MultihostEngine(engine)
        # Recovery on a pod is an epoch resync, not a rebuild: the
        # wrapper drops local work and broadcasts an epoch bump so
        # followers resynchronize (scheduler-death faults only; a
        # truly wedged native collective goes fatal immediately — the
        # stuck thread still owns the engine — see docs/inference.md).
        engine_factory = engine.resync
        if not engine.is_primary:
            # Followers never open a port: they mirror the primary's
            # command stream until it broadcasts shutdown. The fault
            # budget mirrors the primary's restart budget — 0 keeps
            # the loud crash-on-exception contract on both sides.
            engine.serve_forever(fault_budget=args.restart_budget,
                                 fault_window=args.restart_window)
            return 0
    serve(
        cfg, params,
        host=args.host, port=args.port,
        tokenizer=get_tokenizer(args.tokenizer),
        model_name=(args.model or "shellac_tpu"),
        engine=engine,
        engine_factory=engine_factory,
        n_slots=args.slots, max_len=args.max_len,
        temperature=args.temperature, eos_id=args.eos_id,
        decode_ticks=args.decode_ticks,
        overlap_decode=args.overlap_decode,
        overlap_prefill=args.overlap_prefill,
        autotune=True,
        max_prefills_per_step=args.max_prefills_per_step,
        prefill_chunk=args.prefill_chunk,
        logprobs=args.logprobs,
        top_logprobs=args.top_logprobs,
        cache_backend=backend_name,
        step_timeout=args.step_timeout,
        max_pending=args.max_pending,
        restart_budget=args.restart_budget,
        restart_window=args.restart_window,
        heartbeat_path=args.heartbeat_file,
        debug=args.debug,
        debug_include_text=args.debug_include_text,
        profile_dir=args.profile_dir,
        role=args.role,
        spool_dir=args.spool_dir,
        spool_max_bytes=args.spool_max_bytes,
        incident_dir=args.incident_dir,
        incident_rate=args.incident_rate,
        incident_window=args.incident_window,
        incident_retention=args.incident_retention,
        incident_capture_seconds=args.incident_capture_seconds,
        park_dir=args.park_dir,
        park_max_bytes=args.park_max_bytes,
        tenant_config=_load_tenant_config(args.tenant_config),
        preempt_after=args.preempt_after,
    )
    return 0


def _load_tenant_config(value):
    """--tenant-config accepts inline JSON ('{...}') or a file path.
    Returned as raw text either way — TenantPolicy.parse owns the
    actual validation, so a typo dies at startup with its real
    error, not a CLI-side guess at one."""
    if value is None:
        return None
    if value.lstrip().startswith("{"):
        return value
    with open(value) as f:
        return f.read()


def _load_slos(args):
    """Collect SLO specs from repeated --slo flags and/or --slo-file
    (a JSON list of spec strings, or {"slos": [...]}), parsed eagerly
    so a typo dies at startup, not at the first alert."""
    from shellac_tpu.obs import parse_slo_specs

    specs = list(args.slo or [])
    if args.slo_file:
        try:
            with open(args.slo_file) as f:
                data = json.load(f)
        except OSError as e:
            raise SystemExit(f"--slo-file {args.slo_file}: {e}")
        except ValueError as e:
            raise SystemExit(
                f"--slo-file {args.slo_file}: not valid JSON ({e}); "
                'expected a list of spec strings or {"slos": [...]}'
            )
        if isinstance(data, dict):
            data = data.get("slos", [])
        if not isinstance(data, list):
            raise SystemExit(
                f"--slo-file {args.slo_file}: expected a JSON list of "
                'spec strings or {"slos": [...]}'
            )
        specs.extend(str(s) for s in data)
    try:
        return parse_slo_specs(specs)
    except ValueError as e:
        raise SystemExit(f"--slo: {e}")


def cmd_serve_tier(args):
    from shellac_tpu.inference.tier import TierRouter, serve_tier

    if not args.metrics:
        from shellac_tpu.obs import get_registry

        get_registry().disable()
    autoscale = None
    if args.autoscale:
        from shellac_tpu.inference.autoscale import AutoscalePolicy

        autoscale = AutoscalePolicy(
            min_replicas=args.autoscale_min,
            max_replicas=args.autoscale_max,
            cooldown_s=args.autoscale_cooldown,
            idle_after_s=args.autoscale_idle_after,
        )
    router = TierRouter(
        args.replica,
        health_interval=args.health_interval,
        health_timeout=args.health_timeout,
        breaker_failures=args.breaker_failures,
        breaker_window=args.breaker_window,
        breaker_cooldown=args.breaker_cooldown,
        max_attempts=args.max_attempts,
        backoff_base=args.backoff_base,
        backoff_cap=args.backoff_cap,
        default_timeout=args.default_timeout,
        affinity_tolerance=args.affinity_tolerance,
        debug=args.debug,
        federate=args.federate,
        stale_after=args.stale_after,
        slos=_load_slos(args),
        disagg=args.disagg,
        kv_bandwidth=args.kv_bandwidth,
        disagg_min_prompt=args.disagg_min_prompt,
        fabric=args.fabric,
        fabric_hot_hits=args.fabric_hot_hits,
        fabric_max_push=args.fabric_max_push,
        spool_dir=args.spool_dir,
        spool_max_bytes=args.spool_max_bytes,
        incident_dir=args.incident_dir,
        incident_rate=args.incident_rate,
        incident_window=args.incident_window,
        incident_retention=args.incident_retention,
        tenant_config=_load_tenant_config(args.tenant_config),
        autoscale=autoscale,
    )
    serve_tier(router, host=args.host, port=args.port)
    return 0


def cmd_top(args):
    # Deliberately jax-free: `top` is an operator tool that must start
    # instantly on any box with Python, not just an accelerator host.
    from shellac_tpu.obs.top import run_top

    if args.tier is None and not (args.trace and args.spool):
        raise SystemExit(
            "top needs --tier (live dashboard) or --trace with "
            "--spool (recover a dead replica's timeline from disk)"
        )
    return run_top(args.tier, once=args.once, interval=args.interval,
                   trace=args.trace, timeout=args.timeout,
                   spool=args.spool)


def cmd_trace_report(args):
    # jax-free like `top`: reading a capture must work anywhere.
    from shellac_tpu.obs import tracereport

    try:
        if args.diff:
            a, b = args.diff
            result = tracereport.diff(
                tracereport.analyze(a, top=args.top),
                tracereport.analyze(b, top=args.top),
                threshold=args.threshold, min_us=args.min_us,
                phase_shift_points=args.phase_shift_points,
            )
            print(json.dumps(result, indent=1) if args.json
                  else tracereport.render_diff(result), end="")
            # Non-zero on flagged regressions so the diff gates (the
            # ROADMAP item 3 re-measure campaign's comparison step).
            return 0 if result["ok"] else 2
        if not args.capture:
            raise SystemExit(
                "trace-report needs a capture path (or --diff A B)"
            )
        report = tracereport.analyze(args.capture, top=args.top)
        print(json.dumps(report, indent=1) if args.json
              else tracereport.render_report(report), end="")
        return 0
    except (OSError, EOFError, ValueError) as e:
        # OSError covers missing files AND gzip.BadGzipFile; EOFError
        # is a TRUNCATED gzip — exactly what a crash mid-capture
        # leaves behind, so it must fail cleanly, not traceback.
        raise SystemExit(f"trace-report: {e}")


def cmd_scenarios(args):
    """Scenario-matrix SLO gate: workload-model traffic x chaos x
    per-scenario SLO assertions, verdicts folded into
    SCENARIO_LEDGER.json (docs/scenarios.md)."""
    from shellac_tpu.inference import scenarios

    return scenarios.cli_run(args)


def cmd_convert(args):
    """HF checkpoint directory -> native orbax params + config JSON."""
    import dataclasses as dc
    import os

    import orbax.checkpoint as ocp

    from shellac_tpu.models.convert import from_hf

    cfg, params = from_hf(args.hf_dir)
    out = os.path.abspath(args.out)
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(os.path.join(out, "params"), params, force=True)
    ckptr.wait_until_finished()
    cfg_dict = dc.asdict(cfg)
    with open(os.path.join(out, "config.json"), "w") as f:
        json.dump(cfg_dict, f, indent=2)
    n = sum(int(np.prod(x.shape)) for x in
            __import__("jax").tree.leaves(params))
    print(json.dumps({"out": out, "params": n,
                      "model_type": "moe" if cfg.moe else "dense"}))
    return 0


def cmd_info(args):
    import jax

    from shellac_tpu.models import transformer
    from shellac_tpu.models.registry import PRESETS

    if args.model or args.config:
        cfg = _model_config(args)
        shapes = jax.eval_shape(
            lambda: transformer.init_params(cfg, jax.random.PRNGKey(0))
        )
        n = sum(int(np.prod(s.shape)) for s in jax.tree.leaves(shapes))
        print(json.dumps({
            "config": dataclasses.asdict(cfg),
            "params": n,
            "ff_dim": cfg.ff_dim,
            "head_dim": cfg.dim_per_head,
            "kv_heads": cfg.kv_heads,
        }, indent=2))
    else:
        print(json.dumps(sorted(PRESETS), indent=2))
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="shellac_tpu", description=__doc__)
    sub = p.add_subparsers(dest="command", required=True)

    def common(sp):
        sp.add_argument("--model", default="tiny",
                        help="preset name (see `info`)")
        sp.add_argument("--config", help="JSON file of ModelConfig overrides "
                        '(may include {"preset": name})')
        sp.add_argument("--seed", type=int, default=0)

    t = sub.add_parser("train", help="train a model")
    common(t)
    t.add_argument("--steps", type=int, default=100)
    t.add_argument("--batch", type=int, default=8)
    t.add_argument("--seq", type=int, default=128)
    t.add_argument("--data", nargs="*", default=None,
                   help="token shard files (default: synthetic stream)")
    t.add_argument("--mesh", default="",
                   help="mesh axes, e.g. dp=2,fsdp=2,tp=2")
    t.add_argument("--ckpt-dir")
    t.add_argument("--ckpt-every", type=int, default=500)
    t.add_argument("--log-path")
    t.add_argument("--log-every", type=int, default=10)
    t.add_argument("--metrics-file", default=None, dest="metrics_file",
                   help="write the shared metrics-registry snapshot "
                        "(shellac_train_* gauges, step-interval "
                        "histogram) as JSON when training finishes")
    t.add_argument("--heartbeat-file", default=None, dest="heartbeat_file",
                   help="liveness file the training loop touches at "
                        "1 Hz at step boundaries (forced beats bracket "
                        "anomaly rollback/restore), for external "
                        "watchdogs — matches serve --heartbeat-file")
    t.add_argument("--anomaly-action", default="rollback",
                   dest="anomaly_action",
                   choices=["warn", "skip", "rollback", "fatal"],
                   help="what the anomaly sentinel does about a "
                        "non-finite/spiking loss: rollback (default) "
                        "restores the last-good checkpoint and replays "
                        "the data stream; see docs/training.md "
                        "failure semantics")
    t.add_argument("--max-restores", type=int, default=2,
                   dest="max_restores",
                   help="skip/rollback recoveries allowed per hour "
                        "before the sentinel escalates to fatal "
                        "(0 = first anomaly is fatal)")
    t.add_argument("--learning-rate", type=float, dest="learning_rate")
    t.add_argument("--warmup-steps", type=int, dest="warmup_steps")
    t.add_argument("--weight-decay", type=float, dest="weight_decay")
    t.add_argument("--grad-accum", type=int, dest="grad_accum")
    t.add_argument("--optimizer", choices=["adamw", "lion", "adafactor", "muon"])
    t.add_argument("--quant", choices=["int8", "int8_bwd"], default=None,
                   help="quantized training compute (int8 MXU dots; "
                        "int8_bwd quantizes the backward matmuls too)")
    t.add_argument("--ema-decay", type=float, default=None, dest="ema_decay",
                   help="keep an EMA of the weights (e.g. 0.999)")
    t.add_argument("--lora-rank", type=int, default=None, dest="lora_rank",
                   help="LoRA fine-tuning: adapter rank (enables adapter-"
                        "only training; --ckpt-dir then stores adapters)")
    t.add_argument("--lora-alpha", type=float, default=16.0,
                   dest="lora_alpha")
    t.add_argument("--lora-targets", default="wq,wk,wv,wo",
                   dest="lora_targets",
                   help="comma list of wq,wk,wv,wo,w_gate,w_up,w_down")
    t.add_argument("--base-ckpt", default=None, dest="base_ckpt",
                   help="frozen base weights for --lora-rank (a regular "
                        "train checkpoint dir; default: random init)")
    t.set_defaults(fn=cmd_train)

    d = sub.add_parser("dpo", help="preference fine-tuning (DPO)")
    common(d)
    d.add_argument("--data", required=True,
                   help='JSONL of {"prompt","chosen","rejected"} pairs '
                        "(token-id lists, or text with --tokenizer)")
    d.add_argument("--tokenizer", action="store_true",
                   help="rows hold text; encode with the byte tokenizer")
    d.add_argument("--steps", type=int, default=100)
    d.add_argument("--batch", type=int, default=8)
    d.add_argument("--max-len", type=int, default=128, dest="max_len")
    d.add_argument("--beta", type=float, default=0.1)
    d.add_argument("--loss-type", default="sigmoid", dest="loss_type",
                   choices=["sigmoid", "ipo", "hinge"])
    d.add_argument("--label-smoothing", type=float, default=0.0,
                   dest="label_smoothing")
    d.add_argument("--reference-free", action="store_true",
                   dest="reference_free")
    d.add_argument("--mesh", default="",
                   help="mesh axes, e.g. dp=2,fsdp=2,tp=2")
    d.add_argument("--base-ckpt", default=None, dest="base_ckpt",
                   help="starting policy weights (a train checkpoint "
                        "dir; also the frozen reference)")
    d.add_argument("--ckpt-dir")
    d.add_argument("--ckpt-every", type=int, default=500)
    d.add_argument("--log-path")
    d.add_argument("--log-every", type=int, default=10)
    d.add_argument("--learning-rate", type=float, dest="learning_rate")
    d.add_argument("--warmup-steps", type=int, dest="warmup_steps")
    d.add_argument("--weight-decay", type=float, dest="weight_decay")
    d.add_argument("--optimizer",
                   choices=["adamw", "lion", "adafactor", "muon"])
    d.set_defaults(fn=cmd_dpo)

    kd = sub.add_parser("distill",
                        help="distill a teacher checkpoint into a student")
    common(kd)
    kd.add_argument("--teacher-model", default=None, dest="teacher_model",
                    help="teacher preset (default: same config as the "
                         "student)")
    kd.add_argument("--teacher-ckpt", default=None, dest="teacher_ckpt",
                    help="teacher train checkpoint dir (default: seeded "
                         "random weights — useful only for smoke tests)")
    kd.add_argument("--kd-temperature", type=float, default=2.0,
                    dest="kd_temperature")
    kd.add_argument("--alpha", type=float, default=0.5,
                    help="KD weight; (1-alpha) goes to hard-target CE")
    kd.add_argument("--kind", choices=["forward", "reverse"],
                    default="forward")
    kd.add_argument("--steps", type=int, default=100)
    kd.add_argument("--batch", type=int, default=8)
    kd.add_argument("--seq", type=int, default=128)
    kd.add_argument("--data", nargs="*", default=None,
                    help="token shard files (default: synthetic stream)")
    kd.add_argument("--mesh", default="")
    kd.add_argument("--ckpt-dir")
    kd.add_argument("--ckpt-every", type=int, default=500)
    kd.add_argument("--log-path")
    kd.add_argument("--log-every", type=int, default=10)
    kd.add_argument("--learning-rate", type=float, dest="learning_rate")
    kd.add_argument("--warmup-steps", type=int, dest="warmup_steps")
    kd.add_argument("--weight-decay", type=float, dest="weight_decay")
    kd.add_argument("--optimizer",
                    choices=["adamw", "lion", "adafactor", "muon"])
    kd.set_defaults(fn=cmd_distill)

    e = sub.add_parser("eval", help="perplexity of a checkpoint")
    common(e)
    e.add_argument("--ema", action="store_true",
                   help="evaluate the EMA-averaged weights")
    e.add_argument("--batch", type=int, default=8)
    e.add_argument("--seq", type=int, default=128)
    e.add_argument("--batches", type=int, default=16)
    e.add_argument("--data", nargs="*", default=None)
    e.add_argument("--ckpt-dir")
    e.add_argument("--lora-dir", default=None, dest="lora_dir",
                   help="merge adapters from a train --lora-rank dir")
    e.set_defaults(fn=cmd_eval)

    g = sub.add_parser("generate", help="sample tokens")
    common(g)
    g.add_argument("--prompt",
                   help="comma-separated token ids, e.g. 1,5,42")
    g.add_argument("--text", help="text prompt (encoded with --tokenizer)")
    g.add_argument("--tokenizer", default="byte",
                   help='"byte" or a local HF tokenizer dir')
    g.add_argument("--max-new", type=int, default=32)
    g.add_argument("--temperature", type=float, default=1.0)
    g.add_argument("--top-k", type=int, default=None)
    g.add_argument("--top-p", type=float, default=None)
    g.add_argument("--num-beams", type=int, default=None, dest="num_beams",
                   help="beam search with N beams (deterministic; "
                        "ignores temperature/top-k/top-p)")
    g.add_argument("--length-penalty", type=float, default=1.0,
                   dest="length_penalty",
                   help="beam ranking divides scores by len^alpha "
                        "(0 = raw sum, 1 = mean logprob)")
    g.add_argument("--eos-id", type=int, default=None, dest="eos_id",
                   help="EOS token id for beam finishing")
    g.add_argument("--json-schema", default=None, dest="json_schema",
                   help="JSON schema (inline, or @file) compiled to a "
                        "token-DFA constraint for beam search: every "
                        "returned beam satisfies the schema. Needs "
                        "--num-beams and --eos-id")
    g.add_argument("--ckpt-dir")
    g.add_argument("--native-dir", dest="native_dir",
                   help="directory written by `convert`")
    g.add_argument("--quantize", action="store_true",
                   help="int8 weight-only quantization")
    g.add_argument("--kv-quant", choices=["int8"], default=None,
                   dest="kv_quant",
                   help="int8 KV cache (not with --draft-model)")
    g.add_argument("--ema", action="store_true",
                   help="generate with the EMA-averaged weights")
    g.add_argument("--stop", default=None,
                   help='token-id stop sequences, e.g. "13,10;0"')
    g.add_argument("--stop-text", default=None, nargs="*",
                   help="string stop sequences (encoded with --tokenizer)")
    g.add_argument("--draft-model", default=None,
                   help="draft preset for speculative decoding")
    g.add_argument("--gamma", type=int, default=4)
    g.add_argument("--lora-dir", default=None, dest="lora_dir",
                   help="merge adapters from a train --lora-rank dir")
    g.set_defaults(fn=cmd_generate)

    b = sub.add_parser("batch",
                       help="offline batch generation (JSONL in/out)")
    common(b)
    b.add_argument("--input", required=True,
                   help='JSONL rows: {"prompt": text-or-ids, '
                        '"max_tokens"?, "temperature"?, "seed"?, '
                        '"stop"?, ...}')
    b.add_argument("--output", required=True, help="JSONL results path")
    b.add_argument("--max-new", type=int, default=64,
                   help="default max tokens when a row has none")
    b.add_argument("--slots", type=int, default=8)
    b.add_argument("--max-len", type=int, default=None, dest="max_len")
    b.add_argument("--temperature", type=float, default=0.0)
    b.add_argument("--eos-id", type=int, default=None, dest="eos_id")
    b.add_argument("--decode-ticks", type=_decode_ticks_arg, default=4,
                   dest="decode_ticks",
                   help="decode steps per host sync, or 'auto' to "
                        "sweep before the drain")
    b.add_argument("--overlap-decode", dest="overlap_decode",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="overlapped window dispatch during the drain")
    b.add_argument("--overlap-prefill", dest="overlap_prefill",
                   action=argparse.BooleanOptionalAction, default=True,
                   help="in-flight prefill pipeline during the drain "
                        "(admissions dispatch without syncing; one "
                        "batched settle per step boundary)")
    b.add_argument("--mesh", default="", help="e.g. tp=4")
    b.add_argument("--cache-backend", default=None, dest="cache_backend",
                   choices=["dense", "dense-int8", "paged", "paged-int8",
                            "rolling", "rolling-int8"],
                   help="KV-cache storage policy (the registry the "
                        "engines resolve through; see docs/inference.md "
                        "capability table)")
    b.add_argument("--kv-quant", choices=["int8"], default=None,
                   dest="kv_quant",
                   help="deprecated alias for --cache-backend "
                        "dense-int8 (composes with --rolling-window)")
    b.add_argument("--rolling-window", action="store_true",
                   dest="rolling_window",
                   help="deprecated alias for --cache-backend rolling")
    b.add_argument("--logprobs", action="store_true")
    b.add_argument("--tokenizer", default="byte")
    b.add_argument("--ckpt-dir")
    b.add_argument("--lora-dir", default=None, dest="lora_dir")
    b.set_defaults(fn=cmd_batch)

    s = sub.add_parser("serve", help="HTTP server with continuous batching")
    common(s)
    s.add_argument("--host", default="127.0.0.1")
    s.add_argument("--port", type=int, default=8000)
    s.add_argument("--slots", type=int, default=8)
    s.add_argument("--max-len", type=int, default=None, dest="max_len")
    s.add_argument("--temperature", type=float, default=0.0)
    s.add_argument("--eos-id", type=int, default=None, dest="eos_id")
    s.add_argument("--role", choices=["monolith", "prefill", "decode"],
                   default="monolith",
                   help="disaggregated-serving role, reflected in "
                        "/health, /stats, /metrics "
                        "(shellac_engine_role_info) and `top`: the "
                        "tier pairs prefill replicas (run the prompt, "
                        "export KV) with decode replicas (import KV, "
                        "stream tokens). Advisory — every role still "
                        "serves the full API, so monolithic fallback "
                        "always has a target (docs/serving_tier.md)")
    s.add_argument("--cache-backend", default=None, dest="cache_backend",
                   choices=["dense", "dense-int8", "paged", "paged-int8",
                            "rolling", "rolling-int8"],
                   help="KV-cache storage policy, resolved through the "
                        "same backend registry the engines use (the "
                        "legacy --paged/--kv-quant/--rolling-window "
                        "flags are deprecated aliases onto these names; "
                        "see docs/inference.md for the engine x backend "
                        "capability table)")
    s.add_argument("--paged", action="store_true",
                   help="deprecated alias for --cache-backend paged "
                        "(paged-int8 with --kv-quant)")
    s.add_argument("--mesh", default="",
                   help="serve sharded, e.g. tp=4 (multi-host: multiply "
                        "out to the global device count and set the "
                        "JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES/"
                        "JAX_PROCESS_ID env on every process)")
    s.add_argument("--rolling-window", action="store_true",
                   dest="rolling_window",
                   help="deprecated alias for --cache-backend rolling: "
                        "ring-buffer KV cache for sliding-window models "
                        "(cache memory scales with the window, not "
                        "max-len)")
    s.add_argument("--kv-quant", choices=["int8"], default=None,
                   dest="kv_quant",
                   help="deprecated alias selecting the -int8 backend "
                        "variant: half the cache memory and HBM stream "
                        "per decode tick (dense, rolling on uniform "
                        "windows, and paged pools)")
    s.add_argument("--block-size", type=int, default=None, dest="block_size",
                   help="paged pool page size (default 16; int8 pools "
                        "need a multiple of 32 and default to 64)")
    s.add_argument("--prefix-cache", action="store_true", dest="prefix_cache",
                   help="reuse cached KV blocks across prompts sharing a "
                        "prefix (requires a paged backend)")
    s.add_argument("--decode-ticks", type=_decode_ticks_arg,
                   default="auto", dest="decode_ticks",
                   help="decode steps per host sync (throughput vs "
                        "per-token latency): an int, or 'auto' (the "
                        "default) to sweep candidates against the live "
                        "mesh at startup and keep the fastest")
    s.add_argument("--overlap-decode", dest="overlap_decode",
                   action=argparse.BooleanOptionalAction, default=None,
                   help="two-deep decode pipeline: dispatch window k+1 "
                        "while the host settles window k (greedy and "
                        "seeded outputs are token-identical either "
                        "way; --no-overlap-decode restores strict "
                        "ordering; default on, off for --draft-model)")
    s.add_argument("--overlap-prefill", dest="overlap_prefill",
                   action=argparse.BooleanOptionalAction, default=None,
                   help="in-flight prefill pipeline: admissions "
                        "dispatch their prefill and return, and every "
                        "in-flight prefill settles in one batched "
                        "pull at the next step boundary, so a prompt "
                        "burst no longer stalls the decode hot path "
                        "(TTFT is recorded at settle; greedy and "
                        "seeded outputs are token-identical either "
                        "way; default on, off for --draft-model)")
    s.add_argument("--pp-pipeline", action="store_true",
                   dest="pp_pipeline",
                   help="token-level pipelined decode on a pp mesh: "
                        "slot groups stagger across stages so no stage "
                        "idles (slot caches: bf16/int8/rolling; "
                        "n_slots divisible by pp)")
    s.add_argument("--step-timeout", type=float, default=None,
                   dest="step_timeout",
                   help="fail in-flight requests loudly if one engine "
                        "step exceeds this many seconds (wedged "
                        "collective / lost follower detection; with "
                        "--restart-budget the supervisor then rebuilds "
                        "the engine and resumes). Size it above the "
                        "worst compile, including late retraces — see "
                        "docs/inference.md failure semantics")
    s.add_argument("--restart-budget", type=int, default=0,
                   dest="restart_budget",
                   help="auto-recovery: after a wedged step or dead "
                        "scheduler, fail in-flight requests loudly and "
                        "rebuild a fresh engine, up to N times per "
                        "--restart-window before staying fatal "
                        "(0 = fail terminally, the old contract)")
    s.add_argument("--restart-window", type=float, default=300.0,
                   dest="restart_window",
                   help="sliding window (seconds) for --restart-budget; "
                        "a crash-looping engine exhausts the budget "
                        "inside it and the server goes fatal")
    s.add_argument("--max-pending", type=int, default=None,
                   dest="max_pending",
                   help="admission control: reject new requests with "
                        "HTTP 429 + Retry-After once this many are "
                        "pending, instead of queueing unboundedly")
    s.add_argument("--metrics", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="Prometheus metrics + request tracing via "
                        "GET /metrics (on by default; --no-metrics "
                        "no-ops every instrument and the endpoint "
                        "answers 404)")
    s.add_argument("--debug", action=argparse.BooleanOptionalAction,
                   default=True,
                   help="flight-recorder debug endpoints: GET "
                        "/debug/requests (in-flight table, slot "
                        "residency, histogram exemplars), GET "
                        "/debug/request/<trace-id> (event timeline), "
                        "POST /debug/profile (on-demand capture). "
                        "--no-debug answers 404 and disables event "
                        "recording (mirrors --no-metrics)")
    s.add_argument("--debug-include-text", action="store_true",
                   dest="debug_include_text",
                   help="include prompt/generated text in /debug "
                        "responses and recorder events (REDACTED by "
                        "default: debug surfaces must not leak "
                        "transcripts)")
    s.add_argument("--profile-dir", default=None, dest="profile_dir",
                   help="directory for POST /debug/profile?seconds=N "
                        "jax.profiler captures of the live engine "
                        "(unset = the endpoint answers 400; responses "
                        "carry a capture_id/trace_dir that `python -m "
                        "shellac_tpu trace-report` accepts verbatim, "
                        "and ?report=1 inlines the analysis)")
    s.add_argument("--spool-dir", default=None, dest="spool_dir",
                   help="durable event spool: every flight-recorder "
                        "event also appends to a rotating size-capped "
                        "JSONL file here, so a SIGKILL'd replica's "
                        "in-flight timelines survive to disk (recover "
                        "with `top --trace <id> --spool <dir>`; "
                        "redaction rules apply on disk too)")
    s.add_argument("--spool-max-bytes", type=int, default=8 << 20,
                   dest="spool_max_bytes",
                   help="on-disk footprint cap for the event spool "
                        "(active + one rotated file; default 8 MiB)")
    s.add_argument("--park-dir", default=None, dest="park_dir",
                   help="KV park spool: {\"prefill_only\": true, "
                        "\"park\": true} requests export their frozen "
                        "slot as a crc-checked SHLKV1 blob here "
                        "(atomic writes, size-capped LRU), and any "
                        "replica that mounts the same directory can "
                        "{\"resume\": <park_id>} the session — so a "
                        "parked session survives this replica's death "
                        "(unset = park/resume answer 400)")
    s.add_argument("--park-max-bytes", type=int, default=256 << 20,
                   dest="park_max_bytes",
                   help="on-disk footprint cap for the park spool "
                        "(oldest-parked blobs trimmed first; default "
                        "256 MiB)")
    s.add_argument("--tenant-config", default=None, dest="tenant_config",
                   metavar="JSON_OR_PATH",
                   help="per-tenant QoS policy (inline JSON or a file "
                        'path): {"tenants": {name: {rate, burst, '
                        "max_concurrency, priority, weight}}} with an "
                        'optional "default" entry for unlisted '
                        "tenants. Enables per-tenant token-bucket + "
                        "concurrency admission (429 + Retry-After "
                        "over quota) and weighted-fair slot "
                        "scheduling by priority class "
                        "(docs/serving_tier.md#multi-tenancy)")
    s.add_argument("--preempt-after", type=float, default=None,
                   dest="preempt_after",
                   help="seconds a higher-priority request may wait "
                        "with no free slot before the cheapest lower-"
                        "class decode is preempted: frozen mid-"
                        "window, its KV parked, auto-resumed when a "
                        "slot frees — token-identical to an "
                        "unpreempted run, invisible to the victim's "
                        "client except latency (unset = never "
                        "preempt)")
    s.add_argument("--incident-dir", default=None, dest="incident_dir",
                   help="incident black box: supervisor wedge/rebuild, "
                        "restart-budget exhaustion, and POST "
                        "/debug/incident each write an atomic evidence "
                        "bundle here (recorder dump, metrics snapshot, "
                        "in-flight table, step-phase digest, config "
                        "fingerprint; docs/observability.md#incidents)")
    s.add_argument("--incident-rate", type=int, default=6,
                   dest="incident_rate",
                   help="at most this many bundles per "
                        "--incident-window seconds (sliding window; "
                        "dropped triggers are counted, not silent)")
    s.add_argument("--incident-window", type=float, default=600.0,
                   dest="incident_window",
                   help="sliding window (seconds) for --incident-rate")
    s.add_argument("--incident-retention", type=int, default=24,
                   dest="incident_retention",
                   help="bundles kept on disk; oldest deleted beyond "
                        "this")
    s.add_argument("--incident-capture-seconds", type=float, default=0.0,
                   dest="incident_capture_seconds",
                   help="arm an automatic bounded jax.profiler capture "
                        "(through the same one-at-a-time profile lock "
                        "as /debug/profile) on wedge/rebuild incident "
                        "triggers; needs --profile-dir (0 = off)")
    s.add_argument("--heartbeat-file", default=None, dest="heartbeat_file",
                   help="liveness file the serving scheduler touches "
                        "every second, for external watchdogs "
                        "(utils.failure.Heartbeat.is_stale)")
    s.add_argument("--max-prefills-per-step", type=int, default=1,
                   dest="max_prefills_per_step",
                   help="cap prefills per engine step so prompt bursts "
                        "don't stall active decodes")
    s.add_argument("--draft-model", default=None,
                   help="draft preset: serve with speculative decoding "
                        "(dense and paged backends, int8 included; "
                        "not rolling)")
    s.add_argument("--gamma", type=int, default=4,
                   help="draft tokens proposed per verification round")
    s.add_argument("--logprobs", action="store_true",
                   help="track per-token logprobs so requests may ask "
                        "for them")
    s.add_argument("--top-logprobs", type=int, default=0,
                   dest="top_logprobs",
                   help="record N alternative tokens per generated "
                        "token (payload top_logprobs slices down; "
                        "needs --logprobs)")
    s.add_argument("--prefill-chunk", type=_prefill_chunk_arg,
                   default=None, dest="prefill_chunk",
                   help="prefill prompts longer than this incrementally "
                        "(one chunk per step) so a long prompt cannot "
                        "stall active decodes; 'auto' sweeps chunk "
                        "candidates on the live engine at startup and "
                        "keeps the fastest mixed-workload setting (the "
                        "TTFT-vs-TPOT fairness knob, measured)")
    s.add_argument("--ckpt-dir")
    s.add_argument("--lora-dir", default=None, dest="lora_dir",
                   help="merge adapters from a train --lora-rank dir")
    s.add_argument("--quantize", action="store_true")
    s.add_argument("--tokenizer", default="byte")
    s.set_defaults(fn=cmd_serve)

    st = sub.add_parser(
        "serve-tier",
        help="failure-aware router over N serve replicas: health-"
             "checked membership with per-replica circuit breakers, "
             "prefix/session-affinity + load-weighted routing, retry "
             "with backoff+jitter, graceful-drain observation "
             "(docs/serving_tier.md)",
    )
    st.add_argument("--replica", action="append", required=True,
                    metavar="URL",
                    help="replica base URL (repeat per replica), e.g. "
                         "--replica http://10.0.0.1:8000")
    st.add_argument("--host", default="127.0.0.1")
    st.add_argument("--port", type=int, default=8100)
    st.add_argument("--health-interval", type=float, default=0.5,
                    dest="health_interval",
                    help="seconds between /health sweeps of the "
                         "replica set")
    st.add_argument("--health-timeout", type=float, default=2.0,
                    dest="health_timeout",
                    help="per-replica health/metrics request timeout")
    st.add_argument("--breaker-failures", type=int, default=3,
                    dest="breaker_failures",
                    help="failures inside --breaker-window that eject "
                         "a replica from routing")
    st.add_argument("--breaker-window", type=float, default=30.0,
                    dest="breaker_window",
                    help="sliding window (seconds) for the per-replica "
                         "circuit breaker")
    st.add_argument("--breaker-cooldown", type=float, default=5.0,
                    dest="breaker_cooldown",
                    help="seconds an ejected replica waits before one "
                         "half-open health probe may readmit it")
    st.add_argument("--max-attempts", type=int, default=4,
                    dest="max_attempts",
                    help="total attempts per request (first + retries "
                         "on other replicas)")
    st.add_argument("--backoff-base", type=float, default=0.05,
                    dest="backoff_base",
                    help="base of the capped exponential retry backoff "
                         "(full jitter; never outlives the request "
                         "deadline)")
    st.add_argument("--backoff-cap", type=float, default=2.0,
                    dest="backoff_cap",
                    help="ceiling (seconds) of one retry backoff draw")
    st.add_argument("--default-timeout", type=float, default=60.0,
                    dest="default_timeout",
                    help="request deadline when the payload carries no "
                         "timeout; retries stop at the deadline")
    st.add_argument("--affinity-tolerance", type=float, default=4.0,
                    dest="affinity_tolerance",
                    help="load-score gap (roughly queued requests) an "
                         "affinity hit may cost before spilling to the "
                         "least-loaded replica")
    st.add_argument("--metrics", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="Prometheus shellac_tier_* series at /metrics")
    st.add_argument("--debug", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="tier flight-recorder endpoints: GET "
                         "/debug/requests (attempt log tail, e2e "
                         "exemplars) and /debug/request/<trace-id>; "
                         "--no-debug answers 404 and stops recording")
    st.add_argument("--federate", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="re-expose every replica /metrics series on "
                         "the tier's /metrics with a replica label "
                         "(last-known-good through outages, staleness-"
                         "stamped) plus shellac_fleet_* aggregates")
    st.add_argument("--stale-after", type=float, default=5.0,
                    dest="stale_after",
                    help="seconds without a successful replica scrape "
                         "before its federated series are flagged "
                         "stale (they keep serving last-known-good)")
    st.add_argument("--slo", action="append", metavar="SPEC",
                    help="declarative SLO evaluated by multi-window "
                         "burn rate, e.g. 'ttft_p99<500ms@99.9' or "
                         "'availability@99.9' (repeatable; "
                         "docs/observability.md#fleet)")
    st.add_argument("--slo-file", default=None, dest="slo_file",
                    help="JSON file with SLO specs: a list of spec "
                         'strings, or {"slos": [...]}')
    st.add_argument("--disagg", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="disaggregated prefill/decode routing: pair "
                         "a prefill-role replica (runs the prompt, "
                         "exports KV) with a decode-role replica "
                         "(imports KV, streams tokens) per request, "
                         "falling back to monolithic serving when no "
                         "pair exists, the request uses a feature "
                         "that does not migrate, or the estimated "
                         "transfer cost exceeds the measured prefill "
                         "interference. Inert on a fleet without "
                         "role-labeled replicas (serve --role)")
    st.add_argument("--kv-bandwidth", type=float, default=1e9,
                    dest="kv_bandwidth",
                    help="assumed replica-to-replica transfer "
                         "bandwidth in bytes/s for the migration "
                         "cost estimate (est prompt tokens x the "
                         "replica-reported kv_bytes_per_token / this)")
    st.add_argument("--disagg-min-prompt", type=int, default=64,
                    dest="disagg_min_prompt",
                    help="prompts estimated shorter than this many "
                         "tokens always serve monolithically (their "
                         "prefill is cheaper than any migration)")
    st.add_argument("--fabric", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="fleet-wide KV fabric: poll each replica's "
                         "GET /kv/prefixes into a prefix directory, "
                         "route by directory-measured chain overlap "
                         "(a measured hit replaces the discounted "
                         "affinity guess), and proactively replicate "
                         "hot prefix chains to replicas that lack "
                         "them (docs/serving_tier.md#kv-fabric)")
    st.add_argument("--fabric-hot-hits", type=int, default=4,
                    dest="fabric_hot_hits",
                    help="fleet-wide hit count above which a prefix "
                         "chain is hot enough to replicate")
    st.add_argument("--fabric-max-push", type=int, default=2,
                    dest="fabric_max_push",
                    help="replication pushes ordered per health sweep "
                         "(0 keeps the directory routing but never "
                         "pushes)")
    st.add_argument("--spool-dir", default=None, dest="spool_dir",
                    help="durable event spool for the tier's attempt "
                         "log (rotating size-capped JSONL; the "
                         "replica-side serve --spool-dir twin)")
    st.add_argument("--spool-max-bytes", type=int, default=8 << 20,
                    dest="spool_max_bytes",
                    help="on-disk footprint cap for the event spool")
    st.add_argument("--incident-dir", default=None, dest="incident_dir",
                    help="incident black box: SLO page transitions, "
                         "severed streams, exhausted retries, failed "
                         "migrations, and POST /debug/incident each "
                         "write an atomic evidence bundle here — "
                         "including a federated fetch of every "
                         "replica's in-flight table and incident list "
                         "(docs/observability.md#incidents)")
    st.add_argument("--incident-rate", type=int, default=6,
                    dest="incident_rate",
                    help="at most this many bundles per "
                         "--incident-window seconds")
    st.add_argument("--incident-window", type=float, default=600.0,
                    dest="incident_window",
                    help="sliding window (seconds) for --incident-rate")
    st.add_argument("--incident-retention", type=int, default=24,
                    dest="incident_retention",
                    help="bundles kept on disk; oldest deleted beyond "
                         "this")
    st.add_argument("--tenant-config", default=None,
                    dest="tenant_config", metavar="JSON_OR_PATH",
                    help="per-tenant QoS policy enforced at the tier "
                         "edge (same JSON language as serve "
                         "--tenant-config): over-quota tenants get "
                         "429 + Retry-After before their traffic "
                         "reaches any replica, and the tenant id "
                         "rides every forwarded attempt as the "
                         "x-shellac-tenant header")
    st.add_argument("--autoscale",
                    action=argparse.BooleanOptionalAction,
                    default=False,
                    help="SLO-actuated autoscaler: a fast-burn SLO "
                         "page scales out through the replica "
                         "factory; sustained fleet idle drains the "
                         "least-loaded replica — within the "
                         "min/max envelope, one action per cooldown, "
                         "every decision a recorder event + incident "
                         "trigger. Scale-out needs a replica factory "
                         "(programmatic embedders); without one the "
                         "attempt is counted as failed. Default off: "
                         "--no-autoscale tiers are bit-identical to "
                         "pre-autoscaler builds")
    st.add_argument("--autoscale-min", type=int, default=1,
                    dest="autoscale_min",
                    help="replica floor: idle never drains below this")
    st.add_argument("--autoscale-max", type=int, default=4,
                    dest="autoscale_max",
                    help="replica ceiling: pages at the ceiling "
                         "refuse (and keep paging) rather than grow")
    st.add_argument("--autoscale-cooldown", type=float, default=60.0,
                    dest="autoscale_cooldown",
                    help="seconds after ANY action (or failed "
                         "attempt) before the next; absorbs the "
                         "previous action's effect before re-judging")
    st.add_argument("--autoscale-idle-after", type=float,
                    default=300.0, dest="autoscale_idle_after",
                    help="continuous seconds of near-zero per-replica "
                         "load before a scale-down drain")
    st.set_defaults(fn=cmd_serve_tier)

    tp = sub.add_parser(
        "top",
        help="live fleet dashboard over a tier URL: per-replica "
             "routability/pending/KV/p99, SLO burn rates, step-phase "
             "attribution, recent recorder events (--once for a "
             "single snapshot; --trace <id> for one request's "
             "timeline)",
    )
    tp.add_argument("--tier", default=None,
                    help="tier base URL, e.g. http://127.0.0.1:8100 "
                         "(optional with --trace --spool: a dead "
                         "replica's timeline reads from disk alone)")
    tp.add_argument("--once", action="store_true",
                    help="print one snapshot and exit (CI/scripts)")
    tp.add_argument("--interval", type=float, default=2.0,
                    help="refresh interval in seconds")
    tp.add_argument("--timeout", type=float, default=5.0,
                    help="per-endpoint fetch timeout")
    tp.add_argument("--trace", default=None, metavar="TRACE_ID",
                    help="print this trace id's recorded timeline "
                         "instead of the dashboard")
    tp.add_argument("--spool", default=None, metavar="PATH",
                    help="event-spool file or directory (the replica's "
                         "serve --spool-dir): with --trace, recover "
                         "the timeline from disk when the tier lookup "
                         "404s or the replica is dead (no --tier "
                         "needed)")
    tp.set_defaults(fn=cmd_top)

    tr = sub.add_parser(
        "trace-report",
        help="analyze a jax.profiler capture (the *.trace.json.gz a "
             "POST /debug/profile or scripts/profile_step.py "
             "--capture writes): op-level time attribution aligned "
             "with the shellac_step_phase_seconds phases, top-N ops, "
             "fusion counts; --diff A B flags regressions between "
             "two captures and exits non-zero on any "
             "(docs/observability.md#trace-analysis)",
    )
    tr.add_argument("capture", nargs="?", default=None,
                    help="capture directory (a /debug/profile "
                         "trace_dir) or a *.trace.json(.gz) file")
    tr.add_argument("--diff", nargs=2, metavar=("BEFORE", "AFTER"),
                    default=None,
                    help="compare two captures; exit 2 if AFTER "
                         "regressed vs BEFORE")
    tr.add_argument("--top", type=int, default=20,
                    help="ops listed in the report (default 20)")
    tr.add_argument("--threshold", type=float, default=0.15,
                    help="relative regression threshold for --diff "
                         "(default 0.15 = +15%%)")
    tr.add_argument("--min-us", type=float, default=50.0,
                    dest="min_us",
                    help="absolute floor (microseconds) below which "
                         "--diff ignores a change as noise")
    tr.add_argument("--phase-shift-points", type=float, default=0.15,
                    dest="phase_shift_points",
                    help="ABSOLUTE device-share points a phase may "
                         "grow before --diff flags a phase_shift "
                         "(separate from --threshold: shares live on "
                         "a 0..1 scale)")
    tr.add_argument("--json", action="store_true",
                    help="print the report/diff as JSON")
    tr.set_defaults(fn=cmd_trace_report)

    sc = sub.add_parser(
        "scenarios",
        help="scenario-matrix SLO gate: run workload-model traffic "
             "against a replica, assert per-scenario SLOs, fold "
             "verdicts into SCENARIO_LEDGER.json",
    )
    sc.add_argument("--list", action="store_true",
                    help="print the scenario catalog and exit")
    sc.add_argument("--gate", action="store_true",
                    help="run the fast gate subset and compare the "
                         "stable verdict rows against --ledger "
                         "(exit 1 SLO failure, 2 schema drift, "
                         "3 stale ledger)")
    sc.add_argument("--check", action="store_true",
                    help="no traffic: schema-check the committed "
                         "ledger and diff its statically-recomputable "
                         "fields (exit 2 drift, 3 stale)")
    sc.add_argument("--update-ledger", action="store_true",
                    dest="update_ledger",
                    help="run the gate set and rewrite --ledger")
    sc.add_argument("--ledger", default="SCENARIO_LEDGER.json",
                    help="committed baseline path "
                         "(default SCENARIO_LEDGER.json)")
    sc.add_argument("--target", default=None,
                    help="base URL of a live replica/tier to drive; "
                         "default self-hosts tiny in-process replicas")
    sc.add_argument("--scenario", action="append", default=None,
                    help="run only this scenario (repeatable)")
    sc.add_argument("--all", action="store_true",
                    help="include gate=False scenarios (subprocess "
                         "chaos) in the default selection")
    sc.add_argument("--seed", type=int, default=None,
                    help="override every workload seed (changes "
                         "fingerprints: not valid with "
                         "--update-ledger)")
    sc.add_argument("--duration-scale", type=float, default=1.0,
                    dest="duration_scale",
                    help="scale workload durations (burst offsets "
                         "and ramps scale with them)")
    sc.add_argument("--timeout", type=float, default=30.0,
                    help="per-request deadline handed to the server")
    sc.add_argument("--incident-dir", default=None,
                    dest="incident_dir",
                    help="incident bundle directory for self-hosted "
                         "replicas (an SLO breach fires "
                         "POST /debug/incident)")
    sc.add_argument("--induce-violation", action="store_true",
                    dest="induce_violation",
                    help="self-test: swap every assertion for an "
                         "impossible SLO so the gate MUST fail "
                         "(proves a green gate means something)")
    sc.add_argument("--out", default=None,
                    help="write full (non-stable) verdict rows to "
                         "this JSON file")
    sc.set_defaults(fn=cmd_scenarios)

    k = sub.add_parser("tokenize", help="encode text files into a token shard")
    k.add_argument("--input", nargs="+", required=True, help="text files")
    k.add_argument("--output", required=True, help="shard path to write")
    k.add_argument("--tokenizer", default="byte",
                   help='"byte", a trained BPE .json, or a local HF '
                        "tokenizer dir")
    k.add_argument("--train-bpe", type=int, default=None, dest="train_bpe",
                   metavar="VOCAB_SIZE",
                   help="train a byte-level BPE on the inputs first, "
                        "saving it to the --tokenizer path")
    k.set_defaults(fn=cmd_tokenize)

    c = sub.add_parser("convert",
                       help="HF checkpoint dir -> native params + config")
    c.add_argument("--hf-dir", required=True, dest="hf_dir")
    c.add_argument("--out", required=True)
    c.set_defaults(fn=cmd_convert)

    i = sub.add_parser("info", help="presets and config details")
    i.add_argument("--model")
    i.add_argument("--config")
    i.set_defaults(fn=cmd_info)

    # `lint` is dispatched before argparse (see main) so the analysis
    # CLI's option surface is forwarded verbatim and can never drift;
    # this stub only makes it show up in `--help`.
    sub.add_parser(
        "lint", add_help=False,
        help="JAX/TPU-aware static analysis (SH rule set; options: "
             "python -m shellac_tpu.analysis --help)",
    )
    return p


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv[:1] == ["lint"]:
        # One lint engine, two spellings: hand the rest of the command
        # line to the analysis CLI untouched.
        from shellac_tpu.analysis.cli import main as lint_main

        return lint_main(argv[1:])
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
