"""Speculative decoding inside the continuous-batching engine.

The single-request SpeculativeEngine (speculative.py) amortizes the
target model's HBM read over gamma draft proposals; this module brings
the same trick to the serving engines: every engine step runs ONE
verification round over all slots — the draft proposes gamma tokens per
slot, the target scores the gamma+1 window in one forward, and each
slot independently accepts a prefix by rejection sampling (exact-match
accept for greedy slots). A round emits 1..gamma+1 tokens per slot per
host sync, against the base engine's decode_ticks=1 emitting exactly 1.

The speculative behavior is a MIXIN written against the cache-backend
interface (inference/cache), so it composes with storage policies
instead of being welded to the dense engine:

  - `SpeculativeBatchingEngine` — dense/int8 slot caches;
  - `PagedSpeculativeBatchingEngine` — the paged block pool (bf16 or
    int8), including prefix caching and pool admission control.

The TARGET cache is whatever the host engine's backend built; the
verify round's writes and in-window attention reads go through the
same `forward_with_cache` storage dispatch as sequential decode. The
DRAFT always keeps a dense per-slot cache (its own DenseBackend): the
draft model is small, so its cache is not worth paging, and a dense
row rolls back by clamping `lengths` exactly like the single-request
engine.

Sampling composition: per-request temperature, top-k/top-p/min-p,
min_tokens, logit_bias, and per-request seeds all compose. The rule
for every distribution-shaping knob is the same — apply the IDENTICAL
adjustment/truncation to the draft and target distributions before
the acceptance test (ops/sampling.filter_logits_batched is the single
truncation definition, shared with the sequential sampler), and
rejection sampling then reproduces the ADJUSTED target distribution,
which is exactly what sequential decoding samples from.

int8 KV composes too, on both dense and paged pools: the verify
forward WRITES each position's K/V (quantizing at write) before its
in-window attention READS them back through the cache, so the verify
round scores every draft against the same int8-rounded bits
sequential decode re-reads — the acceptance identity holds bit-for-bit
on the shared reference path (greedy parity is pinned by tests).

Remaining exclusions live in EXCLUSIONS below — every raise carries an
`[excluded: <key>]` (or `[pinned: <key>]`) tag that the exclusion-
matrix meta-test (tests/test_cache_backends.py) cross-checks against
this manifest AND against a dedicated test per entry, so an exclusion
can neither rot silently nor be removed without its test noticing.

The reference repo for this project is empty (SURVEY.md §0); there is
no upstream speculative serving engine to cite.
"""

from __future__ import annotations

import time
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.batching import (
    BatchingEngine,
    PagedBatchingEngine,
    _bucket,
)
from shellac_tpu.inference.cache import CacheBackend, DenseBackend
from shellac_tpu.models import transformer
from shellac_tpu.ops.sampling import NEG_INF, filter_logits_batched
from shellac_tpu.parallel.sharding import make_shardings

# The spec-engine exclusion matrix: feature -> why it stays excluded.
# Every entry has (a) a tagged raise in this module and (b) a test in
# tests/test_cache_backends.py::TestExclusionMatrix — the meta-test
# asserts the three stay in lockstep. Burned down in PR 9 from nine
# (rolling, decode_ticks, overlap, int8, pp, constraint, seed,
# prompt_logprobs, all sampling extras) to five; overlap_prefill
# joined when the admission pipeline shipped (same class of survivor
# as overlap_decode — the round accounting leaves no sync to defer).
EXCLUSIONS: Dict[str, str] = {
    "rolling_window": (
        "the verify round re-reads positions a ring may have already "
        "evicted mid-round (a rejected draft's rollback needs the "
        "overwritten rows back)"
    ),
    "overlap_decode": (
        "the host must see each round's per-slot acceptance counts "
        "before it can account the next round, so there is no sync to "
        "defer behind a second in-flight window"
    ),
    "overlap_prefill": (
        "admission fills the draft AND target caches in lockstep "
        "(the draft prefill dispatches from inside _run_prefill), and "
        "the next verify round is accounted against both — there is "
        "no settle to defer without staging the draft cursor through "
        "the flight too"
    ),
    "pp_pipeline": (
        "the verify round replaces the decode scan the pp stage "
        "register pipelines; staging a gamma+1 window through the "
        "register would serialize the stages it exists to overlap"
    ),
    "constraint": (
        "the draft proposes unconstrained tokens, so the verify round "
        "would reject almost everything — a constrained request on a "
        "draft server is a config error, not a slow path; constraining "
        "the draft's proposals through the DFA is the lift that would "
        "remove this"
    ),
    "penalties": (
        "presence/frequency penalties depend on running per-token "
        "counts that change WITH each accepted token inside the round; "
        "supporting them needs per-position count snapshots threaded "
        "through the draft scan and target scoring (deferred — the "
        "identity itself permits it)"
    ),
}

# Knobs pinned by construction rather than excluded compositions.
PINNED: Dict[str, str] = {
    "decode_ticks": (
        "a verify round already emits up to gamma+1 tokens per host "
        "sync; multi-tick windows are the dense engine's answer to the "
        "same problem, so the knob stays 1 ('auto' resolves to 1 and "
        "the startup auto-tuner skips spec engines)"
    ),
}


class _SpecDecodeMixin:
    """Draft-propose / target-verify decode over any cache backend.

    Mixed in FRONT of a BatchingEngine subclass: slot mechanics
    (admission, stop sequences, streaming, per-request sampling
    state) come from the host engine; this mixin replaces prefill
    (adds the draft cache alongside) and `_decode_tokens` (the verify
    round), and widens the admission footprint by gamma+1 (a round
    writes cur + gamma positions before rolling back)."""

    _decode_ticks_tunable = False  # rounds, not tick windows

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        draft_cfg: ModelConfig,
        draft_params: Any,
        *,
        gamma: int = 4,
        **kw,
    ):
        cb = kw.get("cache_backend")
        rolling = bool(kw.get("rolling_window")) or (
            isinstance(cb, str) and cb.startswith("rolling")
        ) or (isinstance(cb, CacheBackend) and cb.is_rolling)
        if rolling:
            raise ValueError(
                "speculative batching does not support rolling caches "
                "[excluded: rolling_window]: the verify round re-reads "
                "positions a ring may have already evicted mid-round"
            )
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError(
                f"target/draft vocab mismatch: {cfg.vocab_size} vs "
                f"{draft_cfg.vocab_size}"
            )
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        # decode_ticks is pinned: a verify round already emits up to
        # gamma+1 tokens per sync. "auto" (the serving default) is
        # accepted and resolves to 1 — _decode_ticks_tunable=False
        # makes the startup auto-tuner skip this engine.
        if kw.get("decode_ticks", 1) not in (1, "auto"):
            raise ValueError(
                "speculative batching emits up to gamma+1 tokens per "
                "step already; decode_ticks must stay 1 "
                "[pinned: decode_ticks]"
            )
        kw["decode_ticks"] = 1
        if kw.get("overlap_decode"):
            raise ValueError(
                "overlap_decode is not wired for the speculative engine "
                "[excluded: overlap_decode]: the host must see each "
                "round's per-slot acceptance counts before it can "
                "account the next round, so there is no sync to defer; "
                "use a non-draft engine for overlapped decode"
            )
        if kw.get("overlap_prefill"):
            raise ValueError(
                "overlap_prefill is not wired for the speculative "
                "engine [excluded: overlap_prefill]: admission fills "
                "the draft and target caches in lockstep and the next "
                "verify round is accounted against both, so there is "
                "no settle to defer; use a non-draft engine for "
                "overlapped prefill"
            )
        if kw.get("pp_pipeline"):
            raise ValueError(
                "pp_pipeline is not wired for the speculative engine "
                "[excluded: pp_pipeline] (its verify round replaces "
                "the decode scan the stage register pipelines; use a "
                "non-draft engine on pp meshes)"
            )
        if kw.get("mesh") is not None:
            tp = kw["mesh"].shape.get("tp", 1)
            if draft_cfg.kv_heads % tp or draft_cfg.n_heads % tp:
                # Fails later anyway, but deep inside device_put with a
                # PartitionSpec message that never names the draft; the
                # draft being smaller than the target makes this the
                # common misconfiguration.
                raise ValueError(
                    f"draft model heads (n_heads={draft_cfg.n_heads}, "
                    f"kv_heads={draft_cfg.kv_heads}) must divide tp={tp} "
                    "— pick a draft with more heads or a smaller tp"
                )
        # The verify round writes cur + gamma positions past the live
        # length before rolling back; admission must keep that span
        # resident (paged: reserved blocks) for every request.
        self.gamma = gamma
        self._footprint_slack = gamma + 1
        super().__init__(cfg, params, **kw)
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        # The draft cache is ALWAYS dense, whatever the target backend:
        # the draft model is small (its cache is not worth paging) and
        # a dense row rolls back by clamping lengths. Built through a
        # backend so its construction/sharding contract matches the
        # target's.
        self._draft_backend = DenseBackend(draft_cfg, self.n_slots,
                                           self.max_len)
        self._dcache = self._draft_backend.init_cache()
        self._dcache_sh = None
        if self.mesh is not None:
            # The draft pins its OWN sharding tree (the target's may be
            # a paged pool with a different pytree); draft params must
            # arrive pre-sharded, same contract as the target's.
            self._dcache_sh = make_shardings(
                self.mesh, self._draft_backend.logical_axes()
            )
            self._dcache = jax.device_put(self._dcache, self._dcache_sh)
        self._draft_prefill_jit = {}
        self._draft_chunk_jit = {}
        # Draft-side chunked-prefill cursor: slot -> tokens of the
        # prompt already in the draft cache. Tracked separately from
        # the target's because a prefix-cache hit starts the TARGET at
        # the matched offset while the draft owns no prefix blocks and
        # must cover the prompt from 0.
        self._draft_chunk_off: Dict[int, int] = {}
        # Reentrancy flag: the paged prefix path runs the target's
        # suffix through _chunk_prefill from inside _run_prefill, which
        # then draft-prefills the WHOLE prompt itself — the wrapper
        # must not also append a bogus draft chunk at the suffix
        # offset.
        self._spec_skip_draft = False
        self._spec_round = None  # built lazily (static sampling flags)
        self.stats.update({
            "spec_rounds": 0,
            "spec_proposed": 0,
            "spec_accepted": 0,
        })

    def _window_write_span(self) -> int:
        # One verify round writes cur + gamma positions per slot.
        return self.gamma + 1

    # ---- admission ---------------------------------------------------

    def submit(self, rid, tokens, max_new: int, stop=None, *,
               temperature=None, top_k=None, top_p=None, min_p=None,
               min_tokens=None, logit_bias=None,
               presence_penalty=None, frequency_penalty=None,
               prompt_logprobs=False, seed=None, constraint=None,
               trace=None) -> None:
        if constraint is not None:
            raise ValueError(
                f"request {rid!r}: structured decoding is not wired "
                "for the speculative engine [excluded: constraint] "
                "(the draft proposes unconstrained tokens, so the "
                "verify round would reject almost everything); use a "
                "non-draft engine"
            )
        if (presence_penalty is not None and presence_penalty != 0.0) or \
                (frequency_penalty is not None and frequency_penalty != 0.0):
            raise ValueError(
                f"request {rid!r}: presence/frequency penalties are "
                "not wired for the speculative engine "
                "[excluded: penalties] (the per-token counts change "
                "with each accepted token inside the round); use a "
                "non-draft engine"
            )
        size = np.asarray(tokens, np.int32).reshape(-1).size
        # A slot finishing mid-round keeps writing the round's window at
        # its frozen tail; reserve gamma+1 slack past the usual budget
        # so those writes stay off other valid positions.
        need = size + max_new + self.gamma + 2
        if need > self.max_len:
            raise ValueError(
                f"request {rid!r}: prompt {size} + max_new {max_new} + "
                f"speculative slack (gamma+2) exceeds max_len {self.max_len}"
            )
        super().submit(rid, tokens, max_new, stop=stop,
                       temperature=temperature, top_k=top_k,
                       top_p=top_p, min_p=min_p, min_tokens=min_tokens,
                       logit_bias=logit_bias,
                       prompt_logprobs=prompt_logprobs, seed=seed,
                       trace=trace)

    # ---- prefill (target via the host engine, plus the draft cache) --

    def _run_prefill(self, slot: int, req):
        # The paged prefix path prefills the target's unmatched SUFFIX
        # via _chunk_prefill; the flag stops the wrapper below from
        # appending a draft chunk at the suffix offset — the draft owns
        # no prefix and prefills the whole prompt right after.
        self._spec_skip_draft = True
        try:
            first_and_lp = super()._run_prefill(slot, req)
        finally:
            self._spec_skip_draft = False
        s = req.tokens.size
        pad = min(_bucket(s), self.max_len)
        if pad not in self._draft_prefill_jit:
            kw = ({"out_shardings": self._dcache_sh}
                  if self._dcache_sh is not None else {})
            # Donate the draft cache (arg 1): the call below rebinds
            # self._dcache from the result, so the slot scatter may
            # write in place instead of copying the whole draft cache.
            self._draft_prefill_jit[pad] = jax.jit(
                self._draft_prefill_impl, donate_argnums=(1,), **kw
            )
        padded = np.zeros((1, pad), np.int32)
        padded[0, :s] = req.tokens
        self._dcache = self._draft_prefill_jit[pad](
            self.draft_params, self._dcache, jnp.asarray(padded),
            jnp.asarray([s], jnp.int32), slot,
        )
        return first_and_lp

    def _draft_prefill_impl(self, dparams, dcache, tokens, prompt_len, slot):
        from shellac_tpu.inference.kvcache import scatter_slot

        mini = self._draft_backend.init_mini(self.max_len)
        _, mini = transformer.forward_with_cache(
            self.draft_cfg, dparams, tokens, mini, new_tokens_len=prompt_len,
            fresh_cache=True, attn_impl=self.attn_impl, mesh=self.mesh,
        )
        return scatter_slot(dcache, mini, slot)

    # ---- chunked prefill (draft cache chunks alongside the target) ---

    def _chunk_prefill(self, pad, fresh, tokens, chunk_len, offset, slot,
                       key, samp, boundary_next=None, want_plp=False):
        """The target chunk program runs via the host engine; the
        draft's cache row is then brought to the SAME coverage, so by
        the final chunk both caches hold the full prompt — identical
        state to the whole-prompt path, which is why chunked spec
        serving stays bit-exact (tests/test_spec_batching.py chunked
        cases). The draft covers the prompt from ITS OWN cursor
        (always 0-origin): a prefix-cache hit starts the target at the
        matched offset, but the draft owns no prefix blocks."""
        out = super()._chunk_prefill(
            pad, fresh, tokens, chunk_len, offset, slot, key, samp,
            boundary_next=boundary_next, want_plp=want_plp,
        )
        if self._spec_skip_draft:
            return out
        req = self._slots[slot]
        # Host ints: these arrays were built from host values on the
        # admission path (no device compute pending behind them).
        t_end = int(np.asarray(offset)[0]) + int(np.asarray(chunk_len)[0])
        dstart = self._draft_chunk_off.get(slot, 0)
        dchunk = req.tokens[dstart:t_end]
        ds = dchunk.size
        if ds > 0:
            dpad = min(_bucket(ds), self.max_len - dstart)
            dfresh = dstart == 0
            jkey = (dpad, dfresh)
            if jkey not in self._draft_chunk_jit:
                jit_kw = ({"out_shardings": self._dcache_sh}
                          if self._dcache_sh is not None else {})
                import functools

                # Same donation contract as the draft prefill:
                # self._dcache is rebound from the result right below.
                self._draft_chunk_jit[jkey] = jax.jit(
                    functools.partial(self._draft_chunk_impl,
                                      fresh=dfresh),
                    donate_argnums=(1,), **jit_kw,
                )
            self._dcache = self._draft_chunk_jit[jkey](
                self.draft_params, self._dcache,
                jnp.asarray(np.pad(dchunk, (0, dpad - ds))[None]),
                jnp.asarray([ds], jnp.int32),
                jnp.asarray([dstart], jnp.int32), slot,
            )
        if t_end >= req.tokens.size:
            self._draft_chunk_off.pop(slot, None)
        else:
            self._draft_chunk_off[slot] = t_end
        return out

    def _draft_chunk_impl(self, dparams, dcache, tokens, chunk_len,
                          offset, slot, *, fresh):
        from shellac_tpu.inference.kvcache import scatter_slot, slot_view

        view = slot_view(dcache, slot, offset)
        _, view = transformer.forward_with_cache(
            self.draft_cfg, dparams, tokens, view,
            new_tokens_len=chunk_len, fresh_cache=fresh,
            attn_impl=self.attn_impl if fresh else "ref", mesh=self.mesh,
        )
        return scatter_slot(dcache, view, slot)

    def _release_slot(self, slot: int) -> None:
        super()._release_slot(slot)
        self._draft_chunk_off.pop(slot, None)

    # ---- one verification round over all slots ----------------------

    def _spec_round_impl(self, params, dparams, tcache, dcache, cur,
                         active, key, samp, use_bias: bool = False,
                         use_seed: bool = False):
        """Returns (tcache, dcache, emitted (B, g+1), counts (B,), cur,
        lps (B, g+1) — zeros unless self.logprobs, top-K value/id
        sidecars, min_rem).

        counts[b] tokens of emitted[b] are real (0 for inactive rows).
        Per-row temperature: greedy rows use the exact-match degenerate
        form; sampled rows use standard rejection sampling over the
        ADJUSTED + FILTERED draft/target distributions — logit_bias and
        the min_tokens EOS ban adjust both sides identically, then
        filter_logits_batched truncates both sides identically (the
        same definition sample_batched uses), so the round reproduces
        exactly the distribution the sequential sampler draws from.
        Inactive rows compute garbage that is frozen (lengths, cur)
        and dropped (counts=0).

        use_seed: rows with seed >= 0 draw every round decision (draft
        proposals, acceptance uniforms, residual, bonus) from
        fold_in(PRNGKey(seed), tokens-generated-so-far) — deterministic
        per request and identical across cache backends, independent of
        co-tenants and the engine's shared stream. (It is NOT the
        sequential engine's seeded stream: a verify round draws a
        different number of variates than a token-by-token sampler.)
        """
        g = self.gamma
        b = cur.shape[0]
        temp, topk, topp, minp, bias, min_rem0, seed_vec, gen0 = samp
        key, kd, kacc, kres, kbonus = jax.random.split(key, 5)
        greedy = temp <= 0.0
        t = jnp.where(greedy, 1.0, temp)[:, None]
        lt0, ld0 = tcache.lengths, dcache.lengths

        def adjust(logits, pos):
            """logit_bias + the min_tokens EOS ban at round-emission
            position `pos` — the same pre-sampler adjustment the base
            engine's _row_decode_step applies, applied to BOTH sides
            so the acceptance identity targets the adjusted
            distribution."""
            x = logits.astype(jnp.float32)
            if use_bias:
                x = x + bias
            if self.eos_id is not None:
                ban = (min_rem0 - pos) > 0
                col = jnp.where(ban, NEG_INF, x[:, self.eos_id])
                x = x.at[:, self.eos_id].set(col)
            return x

        if use_seed:
            # Per-row deterministic key fan: g draft draws + acceptance
            # uniforms + residual + bonus, all derived from (seed,
            # tokens generated before this round).
            def row_keys(s, g0):
                base = jax.random.fold_in(
                    jax.random.PRNGKey(jnp.maximum(s, 0)), g0
                )
                return jax.random.split(base, g + 3)

            rkeys = jax.vmap(row_keys)(seed_vec, gen0)  # (B, g+3, 2)
            seeded = seed_vec >= 0

        def pick_cat(shared_key, per_key_idx, x):
            """Categorical draw: shared-stream rows from `shared_key`,
            seeded rows from their own per-row key."""
            drawn = jax.random.categorical(shared_key, x, axis=-1)
            if use_seed:
                per = jax.vmap(jax.random.categorical)(
                    rkeys[:, per_key_idx], x
                )
                drawn = jnp.where(seeded, per, drawn)
            return drawn

        def dstep(carry, inp):
            k_i, i = inp
            dc, tok = carry
            logits, dc = transformer.forward_with_cache(
                self.draft_cfg, dparams, tok[:, None], dc,
                attn_impl=self.attn_impl, mesh=self.mesh,
            )
            adj = adjust(logits[:, 0], i)
            xq = filter_logits_batched(adj, temp, topk, topp, minp)
            q = jax.nn.softmax(xq, axis=-1)
            nxt = jnp.where(
                greedy,
                jnp.argmax(adj, axis=-1),
                pick_cat(k_i, i, xq),
            ).astype(jnp.int32)
            return (dc, nxt), (nxt, q)

        (dcache, _), (drafts, qs) = jax.lax.scan(
            dstep, (dcache, cur),
            (jax.random.split(kd, g), jnp.arange(g, dtype=jnp.int32)),
        )
        # Backfill the last proposal's kv so the all-accepted case
        # leaves the draft cache complete for the next round.
        _, dcache = transformer.forward_with_cache(
            self.draft_cfg, dparams, drafts[-1][:, None], dcache,
            attn_impl=self.attn_impl, mesh=self.mesh,
        )
        drafts = drafts.T  # (B, g)
        qs = jnp.moveaxis(qs, 0, 1)  # (B, g, V)

        # Target scores [cur, d_0..d_{g-1}] in one forward.
        tin = jnp.concatenate([cur[:, None], drafts], axis=1)  # (B, g+1)
        tlogits, tcache = transformer.forward_with_cache(
            self.cfg, params, tin, tcache, attn_impl=self.attn_impl,
            mesh=self.mesh,
        )
        # Adjusted target logits per emission position, then the SAME
        # truncation as the draft side (rows repeat per position so
        # the per-row filter params line up after the flatten).
        pos = jnp.arange(g + 1, dtype=jnp.int32)
        adj_t = tlogits.astype(jnp.float32)
        if use_bias:
            adj_t = adj_t + bias[:, None, :]
        if self.eos_id is not None:
            ban = (min_rem0[:, None] - pos[None, :]) > 0  # (B, g+1)
            col = jnp.where(ban, NEG_INF, adj_t[:, :, self.eos_id])
            adj_t = adj_t.at[:, :, self.eos_id].set(col)
        rep = lambda v: jnp.repeat(v, g + 1, axis=0)  # noqa: E731
        xp = filter_logits_batched(
            adj_t.reshape(b * (g + 1), -1),
            rep(temp), rep(topk), rep(topp), rep(minp),
        ).reshape(b, g + 1, -1)
        ps = jax.nn.softmax(xp, axis=-1)  # (B, g+1, V) filtered target

        p_d = jnp.take_along_axis(
            ps[:, :g], drafts[..., None], axis=-1
        )[..., 0]
        q_d = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
        u = jax.random.uniform(kacc, (b, g))
        if use_seed:
            u_per = jax.vmap(
                lambda rk: jax.random.uniform(rk, (g,))
            )(rkeys[:, g])
            u = jnp.where(seeded[:, None], u_per, u)
        accept = jnp.where(
            greedy[:, None],
            drafts == jnp.argmax(adj_t[:, :g], axis=-1),
            u * q_d < p_d,
        )
        n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

        # Token after the accepted prefix: residual resample on
        # rejection, bonus sample from the g+1'th target dist otherwise
        # (argmax degenerate forms for greedy rows, on the ADJUSTED
        # unfiltered logits — matching the base engine's greedy path).
        idx = jnp.minimum(n, g - 1)
        p_n = jnp.take_along_axis(ps, idx[:, None, None], axis=1)[:, 0]
        q_n = jnp.take_along_axis(qs, idx[:, None, None], axis=1)[:, 0]
        adj_n = jnp.take_along_axis(
            adj_t, idx[:, None, None], axis=1
        )[:, 0]
        res = jnp.maximum(p_n - q_n, 0.0)
        res_mass = jnp.sum(res, axis=-1, keepdims=True)
        res = jnp.where(res_mass > 1e-9, res, p_n)
        r = jnp.where(
            greedy,
            jnp.argmax(adj_n, axis=-1),
            pick_cat(kres, g + 1, jnp.log(res + 1e-30)),
        ).astype(jnp.int32)
        bonus = jnp.where(
            greedy,
            jnp.argmax(adj_t[:, g], axis=-1),
            pick_cat(kbonus, g + 2, jnp.log(ps[:, g] + 1e-30)),
        ).astype(jnp.int32)
        extra = jnp.where(n < g, r, bonus)

        cols = jnp.arange(g + 1, dtype=jnp.int32)[None, :]
        padded = jnp.concatenate([drafts, extra[:, None]], axis=1)
        emitted = jnp.where(cols == n[:, None], extra[:, None], padded)

        # Roll back: valid history = old length + 1 (cur) + n accepted;
        # inactive rows freeze entirely.
        tcache = tcache.replace(
            lengths=jnp.where(active, lt0 + 1 + n, lt0)
        )
        dcache = dcache.replace(
            lengths=jnp.where(active, ld0 + 1 + n, ld0)
        )
        cur = jnp.where(active, extra, cur)
        counts = jnp.where(active, n + 1, 0)
        # The min_tokens countdown consumed one unit per emitted token.
        min_rem = jnp.where(
            active, jnp.maximum(min_rem0 - counts, 0), min_rem0
        )
        k_tl = self.top_logprobs
        if self.logprobs:
            # Raw-logit log_softmax of each emitted token (cols past
            # counts are garbage the host drops) — Engine convention.
            lsm = jax.nn.log_softmax(tlogits.astype(jnp.float32), axis=-1)
            lps = jnp.take_along_axis(
                lsm, emitted[..., None], axis=-1
            )[..., 0]
            if k_tl:
                # Alternatives per emitted position ride the same
                # verify scoring pass; the host slices by counts like
                # the tokens themselves.
                tlv, tli = jax.lax.top_k(lsm, k_tl)
                tli = tli.astype(jnp.int32)
            else:
                tlv = jnp.zeros((*emitted.shape, 0), jnp.float32)
                tli = jnp.zeros((*emitted.shape, 0), jnp.int32)
        else:
            lps = jnp.zeros(emitted.shape, jnp.float32)
            tlv = jnp.zeros((*emitted.shape, 0), jnp.float32)
            tli = jnp.zeros((*emitted.shape, 0), jnp.int32)
        return (tcache, dcache, emitted, counts, cur, lps, tlv, tli,
                min_rem)

    def _decode_tokens(self, active_rows):
        t0 = time.perf_counter()
        # Backend backstop for the round's write span (paged: grow
        # tables to cover cur + gamma positions; admission already
        # reserved the full slack footprint, so this is the same
        # no-op-in-steady-state check the dense window performs).
        self._pre_decode(active_rows)
        active = jnp.asarray(active_rows)
        self._key, sub = jax.random.split(self._key)
        use_bias = self._sbias is not None and any(
            bb is not None for bb in self._slot_bias
        )
        use_seed = any(
            r is not None and r.seed is not None for r in self._slots
        )
        gen0 = jnp.asarray(
            [len(r.out) if r is not None else 0 for r in self._slots],
            jnp.int32,
        )
        if self._spec_round is None:
            round_kw = (
                {"out_shardings": ((self._cache_sh, self._dcache_sh)
                                   + (None,) * 7)}
                if self._cache_sh is not None else {}
            )
            self._spec_round = jax.jit(
                self._spec_round_impl,
                static_argnames=("use_bias", "use_seed"), **round_kw,
            )
        (self._cache, self._dcache, emitted, counts, self._cur,
         lps, tlv, tli, self._smin) = self._spec_round(
            self.params, self.draft_params, self._cache, self._dcache,
            self._cur, active, sub,
            (self._stemp, self._stopk, self._stopp, self._sminp,
             self._sbias if self._sbias is not None
             else self._zero_bias_row, self._smin, self._sseed, gen0),
            use_bias=use_bias, use_seed=use_seed,
        )
        # The one host sync.
        em, cnt, host_lps, host_tlv, host_tli = jax.device_get(  # shellac: ignore[SH002] — the verify round's ONE packed sync (acceptance counts must reach the host before the next round)
            (emitted, counts, lps, tlv, tli)
        )
        t1 = time.perf_counter()
        # The base engine's window instruments live in _sync_window,
        # which this override replaces: report the verify round as the
        # decode window it is.
        self._sync_block_s += t1 - t0
        self.obs.decode_window_seconds.observe(t1 - t0)
        self.stats["spec_rounds"] += 1
        self.stats["spec_proposed"] += int((cnt > 0).sum()) * self.gamma
        self.stats["spec_accepted"] += int(np.maximum(cnt - 1, 0).sum())
        per_slot = [em[i, :cnt[i]].tolist() for i in range(self.n_slots)]
        if not self.logprobs:
            return per_slot, None, None
        per_lps = [host_lps[i, :cnt[i]].tolist()
                   for i in range(self.n_slots)]
        if not self.top_logprobs:
            return per_slot, per_lps, None
        per_tl = [
            [(host_tli[i, j].tolist(), host_tlv[i, j].tolist())
             for j in range(cnt[i])]
            for i in range(self.n_slots)
        ]
        return per_slot, per_lps, per_tl


class SpeculativeBatchingEngine(_SpecDecodeMixin, BatchingEngine):
    """Speculative continuous batching on the dense-family backends
    ("dense", "dense-int8")."""


class PagedSpeculativeBatchingEngine(_SpecDecodeMixin, PagedBatchingEngine):
    """Speculative continuous batching over the paged block pool
    ("paged", "paged-int8"), prefix caching included: the verify
    round's writes and in-window reads go through the block tables via
    the same forward dispatch sequential paged decode uses, and
    rejected proposals roll back by clamping slot lengths (stale block
    tails self-heal exactly like dense rows). The draft keeps its own
    dense cache — see the module docstring."""
