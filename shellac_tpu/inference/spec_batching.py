"""Speculative decoding inside the continuous-batching engine.

The single-request SpeculativeEngine (speculative.py) amortizes the
target model's HBM read over gamma draft proposals; this class brings
the same trick to the serving engine: every engine step runs ONE
verification round over all slots — the draft proposes gamma tokens per
slot, the target scores the gamma+1 window in one forward, and each
slot independently accepts a prefix by rejection sampling (exact-match
accept for greedy slots). A round emits 1..gamma+1 tokens per slot per
host sync, against the base engine's decode_ticks=1 emitting exactly 1.

Slot mechanics reuse the base engine wholesale (admission, stop
sequences, streaming, per-request temperature): only `_decode_tokens`
and prefill change. The draft keeps its own (L_d, n_slots, ...) cache,
prefilled alongside the target's; rejected proposals roll back by
clamping per-slot cache `lengths` (kvcache.py's write-at-own-length
contract makes the stale tail self-healing), exactly like the
single-request engine.

Greedy output is bit-identical to the plain BatchingEngine and to the
single-request Engine (tested) — speculation, like scheduling, is
invisible to the math. Per-request temperature is supported (the
accept rule vectorizes per row); top_k/top_p/min_p are rejected at
submit because filtering the proposal and target distributions breaks
the rejection-sampling identity.

The reference repo for this project is empty (SURVEY.md §0); there is
no upstream speculative serving engine to cite.
"""

from __future__ import annotations

import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.batching import BatchingEngine, _bucket
from shellac_tpu.inference.kvcache import init_cache
from shellac_tpu.models import transformer


class SpeculativeBatchingEngine(BatchingEngine):
    """Continuous batching with a draft model proposing gamma tokens."""

    _scores_prompts = False  # draft/verify prefill skips prompt scoring
    _decode_ticks_tunable = False  # rounds, not tick windows

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        draft_cfg: ModelConfig,
        draft_params: Any,
        *,
        gamma: int = 4,
        **kw,
    ):
        if kw.get("rolling_window"):
            raise ValueError(
                "speculative batching does not support rolling_window: "
                "the verify round re-reads positions a ring may have "
                "already evicted mid-round"
            )
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError(
                f"target/draft vocab mismatch: {cfg.vocab_size} vs "
                f"{draft_cfg.vocab_size}"
            )
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        # decode_ticks is pinned: a verify round already emits up to
        # gamma+1 tokens per sync. "auto" (the serving default) is
        # accepted and resolves to 1 — _decode_ticks_tunable=False
        # makes the startup auto-tuner skip this engine.
        if kw.get("decode_ticks", 1) not in (1, "auto"):
            raise ValueError(
                "speculative batching emits up to gamma+1 tokens per step "
                "already; decode_ticks must stay 1"
            )
        kw["decode_ticks"] = 1
        if kw.get("overlap_decode"):
            raise ValueError(
                "overlap_decode is not wired for the speculative engine: "
                "the host must see each round's per-slot acceptance "
                "counts before it can account the next round, so there "
                "is no sync to defer; use a non-draft engine for "
                "overlapped decode"
            )
        if kw.get("kv_quant") is not None:
            raise NotImplementedError(
                "speculative batching keeps bf16 caches: the rejection-"
                "sampling identity needs the verify forward's scores to "
                "equal sequential decode's, but the window's in-chunk "
                "attention reads EXACT just-written K/V while sequential "
                "decode re-reads them int8-rounded — see the int8 "
                "section of docs/inference.md for the full argument"
            )
        if kw.get("pp_pipeline"):
            raise ValueError(
                "pp_pipeline is not wired for the speculative engine "
                "(its verify round replaces the decode scan the stage "
                "register pipelines; use a non-draft engine on pp "
                "meshes)"
            )
        super().__init__(cfg, params, **kw)
        if kw.get("mesh") is not None:
            tp = kw["mesh"].shape.get("tp", 1)
            if draft_cfg.kv_heads % tp or draft_cfg.n_heads % tp:
                # Fails later anyway, but deep inside device_put with a
                # PartitionSpec message that never names the draft; the
                # draft being smaller than the target makes this the
                # common misconfiguration.
                raise ValueError(
                    f"draft model heads (n_heads={draft_cfg.n_heads}, "
                    f"kv_heads={draft_cfg.kv_heads}) must divide tp={tp} "
                    "— pick a draft with more heads or a smaller tp"
                )
        self.draft_cfg = draft_cfg
        self.draft_params = draft_params
        self.gamma = gamma
        self._dcache = init_cache(draft_cfg, self.n_slots, self.max_len)
        # The draft cache pins the same sharding tree as the target's
        # (identical logical axes; this engine is dense-cache only) and
        # draft params must arrive pre-sharded, same contract as the
        # target's.
        if self._cache_sh is not None:
            self._dcache = jax.device_put(self._dcache, self._cache_sh)
        self._draft_prefill_jit = {}
        self._draft_chunk_jit = {}
        round_kw = (
            {"out_shardings": (self._cache_sh, self._cache_sh,
                               None, None, None, None, None, None)}
            if self._cache_sh is not None else {}
        )
        self._spec_round = jax.jit(self._spec_round_impl, **round_kw)
        self.stats.update({
            "spec_rounds": 0,
            "spec_proposed": 0,
            "spec_accepted": 0,
        })

    # ---- admission ---------------------------------------------------

    def submit(self, rid, tokens, max_new: int, stop=None, *,
               temperature=None, top_k=None, top_p=None, min_p=None,
               min_tokens=None, logit_bias=None,
               presence_penalty=None, frequency_penalty=None,
               prompt_logprobs=False, seed=None, constraint=None,
               trace=None) -> None:
        if constraint is not None:
            raise ValueError(
                f"request {rid!r}: structured decoding is not wired "
                "for the speculative engine (the draft proposes "
                "unconstrained tokens, so the verify round would "
                "reject almost everything); use a non-draft engine"
            )
        if seed is not None:
            raise ValueError(
                f"request {rid!r}: per-request seed is not wired for "
                "the speculative engine (the draft/verify round has its "
                "own acceptance randomness)"
            )
        if prompt_logprobs:
            raise ValueError(
                f"request {rid!r}: prompt_logprobs is not wired for the "
                "speculative engine"
            )
        if any(v is not None for v in
               (top_k, top_p, min_p, min_tokens, logit_bias,
                presence_penalty, frequency_penalty)):
            raise ValueError(
                f"request {rid!r}: speculative decoding supports "
                "temperature only (distribution filtering/biasing breaks "
                "the rejection-sampling identity)"
            )
        size = np.asarray(tokens, np.int32).reshape(-1).size
        # A slot finishing mid-round keeps writing the round's window at
        # its frozen tail; reserve gamma+1 slack past the usual budget
        # so those writes stay off other valid positions.
        need = size + max_new + self.gamma + 2
        if need > self.max_len:
            raise ValueError(
                f"request {rid!r}: prompt {size} + max_new {max_new} + "
                f"speculative slack (gamma+2) exceeds max_len {self.max_len}"
            )
        super().submit(rid, tokens, max_new, stop=stop,
                       temperature=temperature, trace=trace)

    # ---- prefill (target via base, plus the draft cache) ------------

    def _run_prefill(self, slot: int, req):
        first_and_lp = super()._run_prefill(slot, req)
        s = req.tokens.size
        pad = min(_bucket(s), self.max_len)
        if pad not in self._draft_prefill_jit:
            kw = ({"out_shardings": self._cache_sh}
                  if self._cache_sh is not None else {})
            # Donate the draft cache (arg 1): the call below rebinds
            # self._dcache from the result, so the slot scatter may
            # write in place instead of copying the whole draft cache.
            self._draft_prefill_jit[pad] = jax.jit(
                self._draft_prefill_impl, donate_argnums=(1,), **kw
            )
        padded = np.zeros((1, pad), np.int32)
        padded[0, :s] = req.tokens
        self._dcache = self._draft_prefill_jit[pad](
            self.draft_params, self._dcache, jnp.asarray(padded),
            jnp.asarray([s], jnp.int32), slot,
        )
        return first_and_lp

    def _draft_prefill_impl(self, dparams, dcache, tokens, prompt_len, slot):
        from shellac_tpu.inference.kvcache import scatter_slot

        mini = init_cache(self.draft_cfg, 1, self.max_len)
        _, mini = transformer.forward_with_cache(
            self.draft_cfg, dparams, tokens, mini, new_tokens_len=prompt_len,
            fresh_cache=True, attn_impl=self.attn_impl, mesh=self.mesh,
        )
        return scatter_slot(dcache, mini, slot)

    # ---- chunked prefill (draft cache chunks alongside the target) ---

    def _chunk_prefill(self, pad, fresh, tokens, chunk_len, offset, slot,
                       key, samp, boundary_next=None, want_plp=False):
        """The target chunk program runs via the base engine; the SAME
        chunk then continues the draft cache's row, so by the final
        chunk both caches hold the full prompt — identical state to
        the whole-prompt path, which is why chunked spec serving stays
        bit-exact (tests/test_spec_batching.py chunked cases)."""
        out = super()._chunk_prefill(
            pad, fresh, tokens, chunk_len, offset, slot, key, samp,
            boundary_next=boundary_next, want_plp=want_plp,
        )
        jkey = (pad, fresh)
        if jkey not in self._draft_chunk_jit:
            jit_kw = ({"out_shardings": self._cache_sh}
                      if self._cache_sh is not None else {})
            import functools

            # Same donation contract as the draft prefill: self._dcache
            # is rebound from the result right below.
            self._draft_chunk_jit[jkey] = jax.jit(
                functools.partial(self._draft_chunk_impl, fresh=fresh),
                donate_argnums=(1,), **jit_kw,
            )
        self._dcache = self._draft_chunk_jit[jkey](
            self.draft_params, self._dcache, tokens, chunk_len, offset,
            slot,
        )
        return out

    def _draft_chunk_impl(self, dparams, dcache, tokens, chunk_len,
                          offset, slot, *, fresh):
        from shellac_tpu.inference.kvcache import scatter_slot, slot_view

        view = slot_view(dcache, slot, offset)
        _, view = transformer.forward_with_cache(
            self.draft_cfg, dparams, tokens, view,
            new_tokens_len=chunk_len, fresh_cache=fresh,
            attn_impl=self.attn_impl if fresh else "ref", mesh=self.mesh,
        )
        return scatter_slot(dcache, view, slot)

    # ---- one verification round over all slots ----------------------

    def _spec_round_impl(self, params, dparams, tcache, dcache, cur,
                         active, temp, key):
        """Returns (tcache, dcache, emitted (B, g+1), counts (B,), cur,
        lps (B, g+1) — zeros unless self.logprobs).

        counts[b] tokens of emitted[b] are real (0 for inactive rows).
        Per-row temperature: greedy rows use the exact-match degenerate
        form; sampled rows use standard rejection sampling. Inactive
        rows compute garbage that is frozen (lengths, cur) and dropped
        (counts=0).
        """
        g = self.gamma
        b = cur.shape[0]
        key, kd, kacc, kres, kbonus = jax.random.split(key, 5)
        greedy = temp <= 0.0
        t = jnp.where(greedy, 1.0, temp)[:, None]
        lt0, ld0 = tcache.lengths, dcache.lengths

        def dstep(carry, k):
            dc, tok = carry
            logits, dc = transformer.forward_with_cache(
                self.draft_cfg, dparams, tok[:, None], dc,
                attn_impl=self.attn_impl, mesh=self.mesh,
            )
            logits = logits[:, 0].astype(jnp.float32)
            q = jax.nn.softmax(logits / t, axis=-1)
            nxt = jnp.where(
                greedy,
                jnp.argmax(logits, axis=-1),
                jax.random.categorical(k, logits / t, axis=-1),
            ).astype(jnp.int32)
            return (dc, nxt), (nxt, q)

        (dcache, _), (drafts, qs) = jax.lax.scan(
            dstep, (dcache, cur), jax.random.split(kd, g)
        )
        # Backfill the last proposal's kv so the all-accepted case
        # leaves the draft cache complete for the next round.
        _, dcache = transformer.forward_with_cache(
            self.draft_cfg, dparams, drafts[-1][:, None], dcache,
            attn_impl=self.attn_impl, mesh=self.mesh,
        )
        drafts = drafts.T  # (B, g)
        qs = jnp.moveaxis(qs, 0, 1)  # (B, g, V)

        # Target scores [cur, d_0..d_{g-1}] in one forward.
        tin = jnp.concatenate([cur[:, None], drafts], axis=1)  # (B, g+1)
        tlogits, tcache = transformer.forward_with_cache(
            self.cfg, params, tin, tcache, attn_impl=self.attn_impl,
            mesh=self.mesh,
        )
        ps = jax.nn.softmax(
            tlogits.astype(jnp.float32) / t[..., None], axis=-1
        )  # (B, g+1, V)

        p_d = jnp.take_along_axis(
            ps[:, :g], drafts[..., None], axis=-1
        )[..., 0]
        q_d = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
        u = jax.random.uniform(kacc, (b, g))
        accept = jnp.where(
            greedy[:, None],
            drafts == jnp.argmax(ps[:, :g], axis=-1),
            u * q_d < p_d,
        )
        n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

        # Token after the accepted prefix: residual resample on
        # rejection, bonus sample from the g+1'th target dist otherwise
        # (argmax degenerate forms for greedy rows).
        idx = jnp.minimum(n, g - 1)
        p_n = jnp.take_along_axis(ps, idx[:, None, None], axis=1)[:, 0]
        q_n = jnp.take_along_axis(qs, idx[:, None, None], axis=1)[:, 0]
        res = jnp.maximum(p_n - q_n, 0.0)
        res_mass = jnp.sum(res, axis=-1, keepdims=True)
        res = jnp.where(res_mass > 1e-9, res, p_n)
        r = jnp.where(
            greedy,
            jnp.argmax(p_n, axis=-1),
            jax.random.categorical(kres, jnp.log(res + 1e-30), axis=-1),
        ).astype(jnp.int32)
        bonus = jnp.where(
            greedy,
            jnp.argmax(ps[:, g], axis=-1),
            jax.random.categorical(kbonus, jnp.log(ps[:, g] + 1e-30),
                                   axis=-1),
        ).astype(jnp.int32)
        extra = jnp.where(n < g, r, bonus)

        cols = jnp.arange(g + 1, dtype=jnp.int32)[None, :]
        padded = jnp.concatenate([drafts, extra[:, None]], axis=1)
        emitted = jnp.where(cols == n[:, None], extra[:, None], padded)

        # Roll back: valid history = old length + 1 (cur) + n accepted;
        # inactive rows freeze entirely.
        tcache = tcache.replace(
            lengths=jnp.where(active, lt0 + 1 + n, lt0)
        )
        dcache = dcache.replace(
            lengths=jnp.where(active, ld0 + 1 + n, ld0)
        )
        cur = jnp.where(active, extra, cur)
        counts = jnp.where(active, n + 1, 0)
        k_tl = self.top_logprobs
        if self.logprobs:
            # Raw-logit log_softmax of each emitted token (cols past
            # counts are garbage the host drops) — Engine convention.
            lsm = jax.nn.log_softmax(tlogits.astype(jnp.float32), axis=-1)
            lps = jnp.take_along_axis(
                lsm, emitted[..., None], axis=-1
            )[..., 0]
            if k_tl:
                # Alternatives per emitted position ride the same
                # verify scoring pass; the host slices by counts like
                # the tokens themselves.
                tlv, tli = jax.lax.top_k(lsm, k_tl)
                tli = tli.astype(jnp.int32)
            else:
                tlv = jnp.zeros((*emitted.shape, 0), jnp.float32)
                tli = jnp.zeros((*emitted.shape, 0), jnp.int32)
        else:
            lps = jnp.zeros(emitted.shape, jnp.float32)
            tlv = jnp.zeros((*emitted.shape, 0), jnp.float32)
            tli = jnp.zeros((*emitted.shape, 0), jnp.int32)
        return tcache, dcache, emitted, counts, cur, lps, tlv, tli

    def _decode_tokens(self, active_rows):
        t0 = time.perf_counter()
        active = jnp.asarray(active_rows)
        self._key, sub = jax.random.split(self._key)
        (self._cache, self._dcache, emitted, counts, self._cur,
         lps, tlv, tli) = self._spec_round(
            self.params, self.draft_params, self._cache, self._dcache,
            self._cur, active, self._stemp, sub,
        )
        # The one host sync.
        em, cnt, host_lps, host_tlv, host_tli = jax.device_get(  # shellac: ignore[SH002] — the verify round's ONE packed sync (acceptance counts must reach the host before the next round)
            (emitted, counts, lps, tlv, tli)
        )
        t1 = time.perf_counter()
        # The base engine's window instruments live in _sync_window,
        # which this override replaces: report the verify round as the
        # decode window it is.
        self._sync_block_s += t1 - t0
        self.obs.decode_window_seconds.observe(t1 - t0)
        self.stats["spec_rounds"] += 1
        self.stats["spec_proposed"] += int((cnt > 0).sum()) * self.gamma
        self.stats["spec_accepted"] += int(np.maximum(cnt - 1, 0).sum())
        per_slot = [em[i, :cnt[i]].tolist() for i in range(self.n_slots)]
        if not self.logprobs:
            return per_slot, None, None
        per_lps = [host_lps[i, :cnt[i]].tolist()
                   for i in range(self.n_slots)]
        if not self.top_logprobs:
            return per_slot, per_lps, None
        per_tl = [
            [(host_tli[i, j].tolist(), host_tlv[i, j].tolist())
             for j in range(cnt[i])]
            for i in range(self.n_slots)
        ]
        return per_slot, per_lps, per_tl
