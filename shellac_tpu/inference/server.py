"""Minimal HTTP serving on top of the continuous-batching engine.

Stdlib-only (`http.server`): one scheduler thread owns the
BatchingEngine and is the ONLY thing touching JAX; request handler
threads just enqueue work and wait on per-request events. POSTs block
until their request completes — the concurrency lives in the slot
batch, not in the HTTP layer.

API:
  POST /generate  {"tokens": [1,2,3] | "text": "...", "max_new": 32,
                   "stop": [[7,8], "..."]?,
                   "temperature"/"top_k"/"top_p"/"min_p": per-request
                   sampling overrides (engine defaults otherwise),
                   "min_tokens": ban EOS until N tokens are emitted,
                   "logit_bias": {token id: additive bias},
                   "logprobs": true? (needs an engine built with
                   logprobs=True / serve --logprobs),
                   "n"/"best_of": parallel sampling — best_of
                   completions are generated concurrently (sharing the
                   slot batch) and the n best by mean logprob return as
                   {"choices": [{"tokens", "text"?, "logprobs"?}, ...]}
                   (best_of > n needs --logprobs; greedy rejects n>1)}
                  -> {"id", "tokens", "text"?, "logprobs"?}
                  With "stream": true the response is newline-delimited
                  JSON written as tokens are generated: zero or more
                  {"tokens": [...]} delta lines, then one
                  {"done": true, "tokens": all, "text"?} line. With stop
                  sequences, the longest stop length is held back from
                  deltas so a token that a later match would truncate is
                  never streamed.
  GET  /health    -> {"ok": true, "pending": N}
  GET  /stats     -> engine counters (requests/tokens/steps/prefills,
                     slots busy, decode_ticks)
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
import time
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.batching import BatchingEngine


def _render_plp(plp):
    """Prompt logprobs for a response: position 0 has no predictor and
    renders as null (the OpenAI convention); one definition so the
    n==1, best_of, and streaming shapes cannot drift."""
    return [None] + plp[1:]


class _Pending:
    __slots__ = ("event", "result", "error", "chunks", "emitted", "holdback",
                 "lps", "plp", "tlp", "rid")

    def __init__(self, rid, stream: bool = False, holdback: int = 0):
        self.rid = rid
        self.event = threading.Event()
        self.result = None
        self.error: Optional[str] = None
        # Streaming requests also get a chunk queue: lists of newly
        # generated token ids, then a None sentinel at completion.
        self.chunks: Optional[queue.Queue] = queue.Queue() if stream else None
        self.emitted = 0
        # Tokens withheld from deltas: a stop-sequence match truncates
        # up to max(len(stop)) tokens at the end, so anything closer to
        # the tail than that may still disappear.
        self.holdback = holdback
        # Per-token logprobs of the final result (engines built with
        # logprobs=True deposit them at completion).
        self.lps = None
        self.plp = None  # prompt per-token logprobs (prompt_logprobs)
        self.tlp = None  # per-token top-K alternatives ((ids, lps) pairs)

    def finish(self):
        if self.chunks is not None:
            self.chunks.put(None)
        self.event.set()


class InferenceServer:
    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        tokenizer=None,
        engine: Optional[BatchingEngine] = None,
        model_name: str = "shellac_tpu",
        step_timeout: Optional[float] = None,
        **engine_kw,
    ):
        self.engine = engine or BatchingEngine(cfg, params, **engine_kw)
        self.model_name = model_name
        # Multi-host engines need a step per loop iteration even when
        # idle: follower processes wait inside the command broadcast,
        # and an un-stepped primary would leave them parked in a device
        # collective until its transport times out.
        self._heartbeat = bool(getattr(self.engine, "needs_heartbeat", False))
        self.tokenizer = tokenizer
        self._constraint_cache: "OrderedDict[str, Any]" = OrderedDict()
        self._submit_q: queue.Queue = queue.Queue()
        self._pending: Dict[int, _Pending] = {}
        self._ids = itertools.count()
        self._stop = threading.Event()
        self._fatal: Optional[str] = None
        # Failure detection for hung engine steps. A follower process
        # dying mid-collective leaves the primary's step() WEDGED in
        # native code — no exception ever surfaces, so the scheduler-
        # death path alone cannot save pending requests. The watchdog
        # detects the stall from outside, marks the server failed, and
        # fails everything loudly; the stuck scheduler thread itself is
        # unrecoverable (daemon — it cannot be interrupted from Python)
        # and the operator restarts the pod. serve --step-timeout wires
        # this; single-host deployments usually leave it off (a long
        # prefill compile would trip a short timeout).
        if step_timeout is not None and step_timeout <= 0:
            # Validate BEFORE starting the scheduler thread: raising
            # after start() would orphan an engine-owning daemon thread
            # the caller can never close().
            raise ValueError("step_timeout must be > 0 seconds")
        self.step_timeout = step_timeout
        self._step_started: Optional[float] = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        if step_timeout is not None:
            threading.Thread(target=self._watchdog, daemon=True).start()

    # ---- scheduler thread (sole owner of the engine) ----------------

    def _loop(self):
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            # The scheduler thread is the only consumer; if it dies
            # silently every pending and future request blocks forever.
            # Fail everything loudly instead.
            self._fail_everything(f"scheduler died: {type(e).__name__}: {e}")

    def _fail_everything(self, msg: str) -> None:
        """Mark the server failed: error out every pending and queued
        request and refuse new ones. Called from the scheduler thread
        (on an exception) or the step watchdog (on a wedge) — a benign
        race: whichever runs second finds _pending empty."""
        self._fatal = msg
        self._stop.set()
        for p in list(self._pending.values()):
            p.error = msg
            p.finish()
        self._pending.clear()
        while True:
            try:
                rid, *_ = self._submit_q.get_nowait()
            except queue.Empty:
                break
            p = self._pending.pop(rid, None)
            if p is not None:
                p.error = msg
                p.finish()

    def _watchdog(self) -> None:
        """Detect a wedged engine step (lost follower, dead relay) from
        outside the scheduler thread."""
        poll = min(self.step_timeout / 4, 1.0)
        while not self._stop.is_set():
            started = self._step_started
            if (started is not None
                    and time.monotonic() - started > self.step_timeout):
                self._fail_everything(
                    f"engine step exceeded step_timeout="
                    f"{self.step_timeout}s (wedged collective or lost "
                    "follower); server marked failed — restart the pod"
                )
                return
            self._stop.wait(poll)

    def _process_item(self, item) -> None:
        rid, tokens, max_new, stop, samp = item
        if tokens is None:
            # Cancellation marker: drop queued/in-flight work for an
            # abandoned client request.
            self.engine.cancel(rid)
            p = self._pending.pop(rid, None)
            if p is not None:
                p.error = "cancelled"
                p.finish()
            return
        try:
            self.engine.submit(rid, tokens, max_new, stop=stop, **samp)
        except (ValueError, TypeError) as e:
            # TypeError: unknown sampling kwarg from a programmatic
            # caller — a bad request, not a scheduler-killing fault.
            p = self._pending.pop(rid)
            p.error = str(e)
            p.finish()

    def _run(self):
        while not self._stop.is_set():
            drained = False
            while True:
                try:
                    item = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                drained = True
                self._process_item(item)
            if self.engine.pending or self._heartbeat:
                self._step_started = time.monotonic()
                finished = self.engine.step() or []
                self._step_started = None
                fin = {rid for rid, _ in finished}
                # Stream deltas for requests still in flight. holdback
                # trails the tail by the longest stop length, so a
                # token a later stop match would truncate is never
                # emitted (out only ever shrinks by a matched stop).
                for req in self.engine._slots:
                    if req is None or req.rid in fin:
                        continue
                    p = self._pending.get(req.rid)
                    if p is None or p.chunks is None:
                        continue
                    upto = max(p.emitted, len(req.out) - p.holdback)
                    if upto > p.emitted:
                        p.chunks.put(list(req.out[p.emitted:upto]))
                        p.emitted = upto
                lp_store = getattr(self.engine, "finished_logprobs", {})
                plp_store = getattr(
                    self.engine, "finished_prompt_logprobs", {}
                )
                tl_store = getattr(
                    self.engine, "finished_top_logprobs", {}
                )
                for rid, out in finished:
                    p = self._pending.pop(rid, None)
                    if p is not None:
                        p.result = out
                        p.lps = lp_store.pop(rid, None)
                        p.plp = plp_store.pop(rid, None)
                        p.tlp = tl_store.pop(rid, None)
                        if p.chunks is not None and len(out) > p.emitted:
                            p.chunks.put(list(out[p.emitted:]))
                        p.finish()
                    else:
                        lp_store.pop(rid, None)
                        plp_store.pop(rid, None)
                        tl_store.pop(rid, None)
                if self._heartbeat and not drained and not self.engine.pending:
                    # Idle heartbeat tick: pace the broadcast instead of
                    # spinning the interconnect at full rate.
                    self._stop.wait(0.01)
            elif not drained:
                # Idle: block briefly on the queue instead of spinning.
                # Process in place — re-enqueueing could reorder a
                # submit behind its own cancellation marker.
                try:
                    self._process_item(self._submit_q.get(timeout=0.05))
                except queue.Empty:
                    pass

    # ---- client surface ---------------------------------------------

    def _submit(self, tokens, max_new: int, stop, samp,
                *, stream: bool) -> _Pending:
        if self._fatal is not None:
            raise RuntimeError(self._fatal)
        rid = next(self._ids)
        holdback = max((len(s) for s in stop), default=0) if stop else 0
        p = _Pending(rid, stream=stream, holdback=holdback)
        self._pending[rid] = p
        self._submit_q.put(
            (rid, np.asarray(tokens, np.int32), max_new, stop, samp or {})
        )
        if self._fatal is not None and not p.event.is_set():
            # Scheduler died while we enqueued; its sweep may have
            # missed this request — fail it ourselves.
            self._pending.pop(rid, None)
            raise RuntimeError(self._fatal)
        return p

    def _raise(self, p: _Pending):
        # Scheduler death is a server fault (HTTP 500), not a bad
        # request (400): keep the error classes distinct.
        if self._fatal is not None and p.error == self._fatal:
            raise RuntimeError(p.error)
        raise ValueError(p.error)

    def _await(self, p: _Pending, deadline: Optional[float]) -> _Pending:
        remaining = (None if deadline is None
                     else max(deadline - time.monotonic(), 0.0))
        if not p.event.wait(remaining):
            raise TimeoutError("request timed out")
        if p.error is not None:
            self._raise(p)
        return p

    def _cancel(self, p: _Pending) -> None:
        """Ask the scheduler to drop an unfinished request (tokens=None
        marker); its engine slot frees instead of generating unread
        tokens."""
        if not p.event.is_set():
            self._submit_q.put((p.rid, None, 0, None, None))

    @staticmethod
    def _deadline(timeout) -> Optional[float]:
        return None if timeout is None else time.monotonic() + timeout

    def generate(self, tokens, max_new: int, timeout: Optional[float] = None,
                 stop=None, return_logprobs: bool = False, **samp):
        p = self._submit(tokens, max_new, stop, samp, stream=False)
        try:
            self._await(p, self._deadline(timeout))
        except TimeoutError:
            # Don't strand the slot generating tokens nobody will read.
            self._cancel(p)
            raise
        if return_logprobs:
            return p.result, p.lps, p.plp, p.tlp
        return p.result

    def generate_stream(self, tokens, max_new: int,
                        timeout: Optional[float] = None, stop=None,
                        return_logprobs: bool = False, **samp):
        """Yield ("delta", [token ids]) as generation progresses, then
        ("done", full output) — or ("done", (output, logprobs)) with
        return_logprobs=True. `timeout` bounds the wait per chunk."""
        p = self._submit(tokens, max_new, stop, samp, stream=True)
        finished = False
        try:
            while True:
                try:
                    chunk = p.chunks.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError("request timed out mid-stream")
                if chunk is None:
                    break
                yield ("delta", chunk)
            if p.error is not None:
                self._raise(p)
            finished = True
            yield ("done",
                   (p.result, p.lps, p.plp, p.tlp) if return_logprobs
                   else p.result)
        finally:
            if not finished:
                # Consumer abandoned the stream (client disconnect tears
                # the generator down via GeneratorExit) or it errored:
                # free the slot instead of generating unread tokens.
                self._cancel(p)

    def _parse(self, payload: dict):
        if "tokens" in payload:
            tokens = np.asarray(payload["tokens"], np.int32)
        elif "text" in payload:
            if self.tokenizer is None:
                raise ValueError('"text" needs a server-side tokenizer')
            tokens = self.tokenizer.encode(payload["text"])
        else:
            raise ValueError('need "tokens" or "text"')
        max_new = int(payload.get("max_new", 32))
        stop = payload.get("stop")
        if stop is not None:
            try:
                parsed = []
                for s in stop:
                    if isinstance(s, str):
                        if self.tokenizer is None:
                            raise ValueError(
                                "string stop sequences need a server-side "
                                "tokenizer"
                            )
                        parsed.append(
                            list(map(int, self.tokenizer.encode(s)))
                        )
                    else:
                        parsed.append(list(map(int, s)))
            except (TypeError, ValueError) as e:
                # Malformed payloads must surface as HTTP 400, not a
                # dropped connection.
                raise ValueError(f"bad stop sequences: {e}")
            stop = parsed
        # Per-request sampling overrides (validated by engine.submit;
        # whitelisted so unknown payload keys can't reach **kwargs).
        try:
            samp = {
                k: float(payload[k])
                for k in ("temperature", "top_p", "min_p",
                          "presence_penalty", "frequency_penalty")
                if payload.get(k) is not None
            }
            for key in ("top_k", "min_tokens", "seed"):
                if payload.get(key) is not None:
                    v = float(payload[key])
                    if not v.is_integer():
                        raise ValueError(
                            f"{key} must be an integer, got {v}"
                        )
                    samp[key] = int(v)
            if payload.get("prompt_logprobs"):
                samp["prompt_logprobs"] = True
            if payload.get("logit_bias") is not None:
                lb = payload["logit_bias"]
                if not isinstance(lb, dict):
                    raise ValueError(
                        "logit_bias must be a {token id: bias} object"
                    )
                samp["logit_bias"] = lb  # entries validated by submit
            if payload.get("constraint") is not None:
                samp["constraint"] = self._compile_constraint(
                    payload["constraint"]
                )
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad sampling parameters: {e}")
        return tokens, max_new, stop, samp

    def _compile_constraint(self, spec):
        """Compile a constraint spec ({"regex"|"json_schema"|
        "json_object"}) to a TokenDFA over this server's tokenizer,
        cached per pattern — the compile walks the whole vocab, so a
        repeated schema must not pay it twice."""
        from shellac_tpu.inference.constraints import (
            compile_token_dfa,
            constraint_pattern,
        )

        if self.tokenizer is None:
            raise ValueError(
                "constrained decoding needs a server-side tokenizer "
                "(the grammar compiles against token strings)"
            )
        eos_id = getattr(self.engine, "eos_id", None)
        if eos_id is None:
            raise ValueError(
                "constrained decoding needs the engine's eos_id (serve "
                "--eos-id or a tokenizer that defines one)"
            )
        pattern = constraint_pattern(spec)
        cached = self._constraint_cache.get(pattern)
        if cached is None:
            cached = compile_token_dfa(
                pattern, self.tokenizer, self.engine.cfg.vocab_size,
                eos_id,
            )
            self._constraint_cache[pattern] = cached
            # Client-supplied patterns key this cache: bound it (LRU)
            # so sustained novel schemas cannot grow host memory
            # without limit — each table is O(states x vocab) int32.
            while len(self._constraint_cache) > 32:
                self._constraint_cache.pop(
                    next(iter(self._constraint_cache))
                )
        else:
            self._constraint_cache.move_to_end(pattern)
        return cached

    def _check_logprobs(self, payload) -> bool:
        want = bool(payload.get("logprobs"))
        if want and not getattr(self.engine, "logprobs", False):
            raise ValueError(
                "logprobs requested but the server engine was not built "
                "with logprobs=True (serve --logprobs)"
            )
        return want

    def _check_top_logprobs(self, payload, want_lps: bool) -> int:
        """Per-request k of alternatives to RENDER (0 = none). The
        engine records its configured max for every request; k only
        slices."""
        k = payload.get("top_logprobs")
        if k in (None, 0, False):
            return 0
        k = int(k)
        cap = getattr(self.engine, "top_logprobs", 0)
        if k == 1 and cap == 0 and payload.get("top_logprobs_soft"):
            # OpenAI's completions `logprobs: 1` predates alternative
            # recording here; the completions translator marks it soft
            # so servers without --top-logprobs keep its long-standing
            # meaning (chosen token's logprob, no alternatives block).
            # Explicit chat/native `top_logprobs: 1` stays a loud 400
            # below — a misconfigured server must not silently degrade
            # a request that asked for alternatives by name.
            return 0
        if k < 1 or k > cap:
            raise ValueError(
                f"top_logprobs={k}: this server records "
                f"{cap or 'no'} alternatives (serve --top-logprobs N)"
            )
        if not want_lps:
            raise ValueError("top_logprobs needs logprobs=true")
        return k

    @staticmethod
    def _render_tlp(tlp, k):
        """[(ids, lps)] per token -> [[{'id', 'logprob'}] * k]."""
        return [
            [{"id": int(i), "logprob": float(v)}
             for i, v in zip(ids[:k], vals[:k])]
            for ids, vals in tlp
        ]

    def handle(self, payload: dict) -> dict:
        tokens, max_new, stop, samp = self._parse(payload)
        want_lps = self._check_logprobs(payload)
        tlk = self._check_top_logprobs(payload, want_lps)
        n, best_of = self._parse_n(payload, samp)
        if n == 1 and best_of == 1:
            out, lps, plp, tlp = self.generate(
                tokens, max_new, timeout=payload.get("timeout"), stop=stop,
                return_logprobs=True, **samp,
            )
            return self._format_completion(
                out, lps, want_lps, plp=plp, tlp=tlp, tlk=tlk,
            )
        # Parallel sampling: best_of independent completions share the
        # slot batch (and, on a paged+prefix engine, their prompt KV);
        # the n best by mean token logprob come back as "choices". The
        # prompt is identical across the fan-out, so prompt logprobs
        # (echo) are computed ONCE, on the first sub-request only.
        rest_samp = {k: v for k, v in samp.items()
                     if k != "prompt_logprobs"}
        pendings = [
            self._submit(tokens, max_new, stop,
                         samp if i == 0 else rest_samp, stream=False)
            for i in range(best_of)
        ]
        # One overall deadline for the whole fan-out — not a fresh
        # clock per completion.
        deadline = self._deadline(payload.get("timeout"))
        choices = []
        plp = None
        try:
            for p in pendings:
                self._await(p, deadline)
                choices.append((p.result, p.lps, p.tlp))
                if p.plp is not None:
                    plp = p.plp
        except (TimeoutError, ValueError, RuntimeError):
            # Don't strand the rest: unfinished siblings would keep
            # occupying slots generating tokens nobody will read.
            for p in pendings:
                self._cancel(p)
            raise
        if best_of > n:
            # Rank by mean logprob (length-normalized); engine logprobs
            # are guaranteed on because _parse_n requires the flag. A
            # completion emptied by a stop match ranks last, not first
            # (an empty mean would otherwise score a perfect 0.0).
            def score(c):
                return (sum(c[1]) / len(c[1])) if c[1] else float("-inf")

            choices.sort(key=score, reverse=True)
        result: Dict[str, Any] = {"choices": [
            self._format_completion(out, lps, want_lps, tlp=tlp, tlk=tlk)
            for out, lps, tlp in choices[:n]
        ]}
        if plp is not None:
            result["prompt_logprobs"] = _render_plp(plp)
        return result

    def _format_completion(self, out, lps, want_lps,
                           plp=None, tlp=None, tlk=0) -> Dict[str, Any]:
        result: Dict[str, Any] = {"tokens": out}
        if want_lps:
            result["logprobs"] = lps
        if tlk and tlp is not None:
            result["top_logprobs"] = self._render_tlp(tlp, tlk)
        if plp is not None:
            result["prompt_logprobs"] = _render_plp(plp)
        if self.tokenizer is not None:
            result["text"] = self.tokenizer.decode(out)
        return result

    def _parse_n(self, payload: dict, samp: dict):
        """Validate n (completions returned) and best_of (sampled)."""
        try:
            n = int(payload.get("n", 1))
            best_of = int(payload.get("best_of", n))
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad n/best_of: {e}")
        if n < 1 or best_of < n:
            raise ValueError(f"need best_of >= n >= 1, got n={n}, "
                             f"best_of={best_of}")
        cap = max(4 * getattr(self.engine, "n_slots", 8), 16)
        if best_of > cap:
            raise ValueError(
                f"best_of={best_of} exceeds this server's cap of {cap} "
                "(4x slot count): one request would monopolize the "
                "engine for every other client"
            )
        if best_of == 1:
            return n, best_of
        temp = samp.get("temperature",
                        getattr(self.engine, "_defaults", {}).get(
                            "temperature", 0.0))
        if temp == 0.0:
            raise ValueError(
                "n/best_of > 1 with greedy sampling would return "
                "identical completions; set a temperature"
            )
        if best_of > n and not getattr(self.engine, "logprobs", False):
            raise ValueError(
                "best_of > n ranks completions by logprob; start the "
                "server with logprobs enabled (serve --logprobs)"
            )
        return n, best_of

    def handle_stream(self, payload: dict):
        """Yield response dicts for a streaming request: delta lines
        {"tokens": [...]}, then {"done": true, "tokens", "text"?,
        "logprobs"?}. Logprobs (when requested) arrive on the final
        record only. Parse errors raise before the first yield (clean
        HTTP 400)."""
        tokens, max_new, stop, samp = self._parse(payload)
        want_lps = self._check_logprobs(payload)
        tlk = self._check_top_logprobs(payload, want_lps)
        n, best_of = self._parse_n(payload, samp)
        if n != 1 or best_of != 1:
            raise ValueError("streaming does not support n/best_of > 1")
        stream = self.generate_stream(
            tokens, max_new, timeout=payload.get("timeout"), stop=stop,
            return_logprobs=True, **samp,
        )
        for kind, val in stream:
            if kind == "delta":
                yield {"tokens": val}
            else:
                out, lps, plp, tlp = val
                final: Dict[str, Any] = {"done": True, "tokens": out}
                if want_lps:
                    final["logprobs"] = lps
                if tlk and tlp is not None:
                    final["top_logprobs"] = self._render_tlp(tlp, tlk)
                if plp is not None:
                    final["prompt_logprobs"] = _render_plp(plp)
                if self.tokenizer is not None:
                    final["text"] = self.tokenizer.decode(out)
                yield final

    def _prompt_lp_capable(self) -> bool:
        eng = self.engine
        if not hasattr(eng, "finished_prompt_logprobs"):
            return False
        # Paged engines score prompts now; out are the prefix cache (a
        # cache hit skips exactly the scoring forward passes) and
        # speculative engines (draft/verify prefill does not score).
        return (getattr(eng, "_scores_prompts", True)
                and not getattr(eng, "prefix_cache", False))

    # ---- OpenAI-compatible façade -----------------------------------

    def handle_openai(self, payload: dict, chat: bool) -> dict:
        from shellac_tpu.inference.openai_api import (
            chat_to_native,
            completion_response,
            completion_to_native,
        )

        native = (chat_to_native(payload, self.tokenizer) if chat
                  else completion_to_native(payload, self.tokenizer))
        echo = bool(native.pop("echo", False))
        if native.get("prompt_logprobs") and not self._prompt_lp_capable():
            raise ValueError(
                "echo with logprobs is unavailable on this server: the "
                "engine cannot score prompts (prefix-cached or "
                "speculative prefill skips the scoring forwards)"
            )
        tokens = self._parse(native)[0]
        # Hand handle() the ids so the prompt is not tokenized twice.
        native.pop("text", None)
        native["tokens"] = [int(t) for t in tokens]
        prompt_tokens = len(tokens)
        max_new = int(native.get("max_new", 32))
        result = self.handle(native)
        return completion_response(
            result, model=self.model_name, prompt_tokens=prompt_tokens,
            max_new=max_new, tokenizer=self.tokenizer, chat=chat,
            echo=echo, prompt_ids=[int(t) for t in tokens],
        )

    def handle_openai_stream(self, payload: dict, chat: bool):
        """Yield OpenAI SSE chunk objects (the HTTP layer frames them
        as `data:` lines and appends [DONE])."""
        from shellac_tpu.inference.openai_api import (
            StreamTranslator,
            chat_to_native,
            completion_to_native,
        )

        native = (chat_to_native(payload, self.tokenizer) if chat
                  else completion_to_native(payload, self.tokenizer))
        if native.pop("echo", False):
            raise ValueError(
                "echo does not compose with streaming (the prompt is "
                "known to the client; request it unstreamed)"
            )
        native.pop("prompt_logprobs", None)
        max_new = int(native.get("max_new", 32))
        translator = StreamTranslator(
            model=self.model_name, tokenizer=self.tokenizer, chat=chat,
        )
        for record in self.handle_stream(native):
            yield from translator.feed(record, max_new)

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2)
        if getattr(self.engine, "is_primary", False):
            # Multi-host: the followers must be released with a STOP
            # broadcast, and only after the scheduler thread (the
            # broadcast's other participant on this process) has truly
            # exited — two threads must not broadcast at once, and a
            # slow step can easily outlive the 2s fast path above. Only
            # a thread wedged WELL beyond a step (dead transport) may
            # leave shutdown unsent; at that point the followers'
            # collectives are failing on their own.
            deadline = time.monotonic() + 300
            while self._thread.is_alive() and time.monotonic() < deadline:
                self._thread.join(timeout=5)
            if not self._thread.is_alive():
                self.engine.shutdown()


def make_http_server(server: InferenceServer, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/v1/models":
                self._send(200, {
                    "object": "list",
                    "data": [{
                        "id": server.model_name, "object": "model",
                        "owned_by": "shellac_tpu",
                    }],
                })
            elif self.path == "/health":
                self._send(200, {"ok": True,
                                 "pending": server.engine.pending})
            elif self.path == "/stats":
                eng = server.engine
                self._send(200, {
                    **eng.stats,
                    "pending": eng.pending,
                    "slots_busy": sum(r is not None for r in eng._slots),
                    "n_slots": eng.n_slots,
                    "decode_ticks": eng.decode_ticks,
                })
            else:
                self._send(404, {"error": "not found"})

        def _stream(self, payload: dict):
            # Newline-delimited JSON, no Content-Length: the connection
            # closes at the end of the stream (HTTP/1.0 semantics of
            # BaseHTTPRequestHandler — no keep-alive to preserve).
            lines = server.handle_stream(payload)
            try:
                first = next(lines)  # parse errors surface before 200
            except StopIteration:
                first = None
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.end_headers()
            rest = (
                itertools.chain([first], lines) if first is not None else lines
            )
            try:
                for obj in rest:
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()
            except OSError:
                # Client hung up mid-stream (the normal cancel path);
                # nothing to report and nobody left to report it to.
                pass
            except (ValueError, TimeoutError, RuntimeError) as e:
                # Headers are gone; report in-band and close.
                try:
                    self.wfile.write(
                        (json.dumps({"error": str(e)}) + "\n").encode()
                    )
                except OSError:
                    pass

        def _stream_sse(self, payload: dict, chat: bool):
            # OpenAI Server-Sent Events framing: one `data: <json>` line
            # per chunk, blank-line separated, closed by `data: [DONE]`.
            chunks = server.handle_openai_stream(payload, chat)
            try:
                first = next(chunks, None)  # errors surface before 200
            except (ValueError, TimeoutError) as e:
                self._send(400, {"error": {"message": str(e),
                                           "type": "invalid_request_error"}})
                return
            except RuntimeError as e:
                # Scheduler death is a server fault, not a bad request.
                self._send(500, {"error": {"message": str(e),
                                           "type": "server_error"}})
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            rest = (
                itertools.chain([first], chunks) if first is not None
                else chunks
            )
            try:
                for obj in rest:
                    self.wfile.write(
                        f"data: {json.dumps(obj)}\n\n".encode()
                    )
                    self.wfile.flush()
                self.wfile.write(b"data: [DONE]\n\n")
            except OSError:
                pass  # client hung up: the engine-side cancel fires
            except (ValueError, TimeoutError, RuntimeError) as e:
                try:
                    self.wfile.write(
                        f"data: {json.dumps({'error': str(e)})}\n\n".encode()
                    )
                except OSError:
                    pass

        def do_POST(self):
            openai_routes = {
                "/v1/completions": False,
                "/v1/chat/completions": True,
            }
            if self.path not in ("/generate", *openai_routes):
                self._send(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path in openai_routes:
                    chat = openai_routes[self.path]
                    if payload.get("stream"):
                        self._stream_sse(payload, chat)
                    else:
                        self._send(200, server.handle_openai(payload, chat))
                elif payload.get("stream"):
                    self._stream(payload)
                else:
                    self._send(200, server.handle(payload))
            except (ValueError, TimeoutError) as e:
                err = {"error": str(e)}
                if self.path in openai_routes:
                    # OpenAI clients expect the nested error shape.
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"}}
                self._send(400, err)
            except RuntimeError as e:
                self._send(500, {"error": str(e)})

    return ThreadingHTTPServer((host, port), Handler)


def serve(cfg: ModelConfig, params, *, host="127.0.0.1", port=8000,
          tokenizer=None, **engine_kw):
    """Blocking entry point used by the CLI."""
    srv = InferenceServer(cfg, params, tokenizer=tokenizer, **engine_kw)
    httpd = make_http_server(srv, host, port)
    print(json.dumps({"serving": f"http://{host}:{httpd.server_address[1]}"}),
          flush=True)
    try:
        httpd.serve_forever()
    finally:
        srv.close()
