"""Minimal HTTP serving on top of the continuous-batching engine.

Stdlib-only (`http.server`): one scheduler thread owns the
BatchingEngine and is the ONLY thing touching JAX; request handler
threads just enqueue work and wait on per-request events. POSTs block
until their request completes — the concurrency lives in the slot
batch, not in the HTTP layer.

A SUPERVISOR wraps the scheduler in engine *generations*: when the
step watchdog trips (wedged collective) or the scheduler thread dies,
every in-flight request fails loudly, the wedged thread is abandoned,
and — within the restart budget — a fresh engine is rebuilt from the
retained params/config under a new generation and serving resumes.
Results a stale generation ever produces are discarded. Admission is
bounded (`max_pending`): over-limit submissions are rejected
immediately (HTTP 429 + Retry-After; `ServerUnavailable` for
programmatic callers) instead of queueing without limit, and a
request's client timeout rides its submit tuple as a deadline — the
scheduler sheds requests whose deadline already expired before
spending prefill compute on them.

API:
  POST /generate  {"tokens": [1,2,3] | "text": "...", "max_new": 32,
                   "stop": [[7,8], "..."]?,
                   "temperature"/"top_k"/"top_p"/"min_p": per-request
                   sampling overrides (engine defaults otherwise),
                   "min_tokens": ban EOS until N tokens are emitted,
                   "logit_bias": {token id: additive bias},
                   "logprobs": true? (needs an engine built with
                   logprobs=True / serve --logprobs),
                   "n"/"best_of": parallel sampling — best_of
                   completions are generated concurrently (sharing the
                   slot batch) and the n best by mean logprob return as
                   {"choices": [{"tokens", "text"?, "logprobs"?}, ...]}
                   (best_of > n needs --logprobs; greedy rejects n>1)}
                  -> {"id", "tokens", "text"?, "logprobs"?}
                  With "stream": true the response is newline-delimited
                  JSON written as tokens are generated: zero or more
                  {"tokens": [...]} delta lines, then one
                  {"done": true, "tokens": all, "text"?} line. With stop
                  sequences, the longest stop length is held back from
                  deltas so a token that a later match would truncate is
                  never streamed.
  GET  /health    -> readiness: 200 {"status": "ok", ...} only while
                  serving; 503 with "recovering" (supervisor mid-
                  rebuild), "draining" (graceful drain in progress),
                  or "failed" (fatal, message included). Always
                  carries pending/queue depth, restart count, shed
                  count, and the engine generation.
  POST /drain     -> admin: flip readiness, refuse new admissions
                  (503 + Retry-After), complete in-flight requests.
                  {"resume": true} cancels the drain. Poll /health
                  until "pending" is 0, then stop the replica.
  GET  /stats     -> engine counters (requests/tokens/steps/prefills,
                     slots busy, decode_ticks) plus supervisor state
                     ("fatal", "status", "restarts", "generation",
                     "shed"), uptime_s, and p50/p90/p99 TTFT /
                     queue-wait / e2e latency digests — stays 200 even
                     when fatal, so scrapers keep collecting through an
                     outage.
  GET  /metrics   -> Prometheus text exposition (shellac_ttft_seconds,
                     shellac_tpot_seconds, shellac_queue_wait_seconds,
                     engine occupancy/utilization, supervisor
                     restart/shed/admission counters — the catalog is
                     docs/observability.md). 404 with --no-metrics;
                     otherwise stays 200 through an outage.
"""

from __future__ import annotations

import concurrent.futures
import functools
import itertools
import json
import os
import queue
import random
import threading
import time
import urllib.parse
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference import disagg, fabric
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.cache import PoolExhausted
from shellac_tpu.inference.qos import (
    ANONYMOUS,
    CLASS_NAMES,
    TENANT_HEADER,
    AdmissionController,
    TenantPolicy,
)
from shellac_tpu.obs import (
    REQUEST_ID_HEADER,
    TRACE_HEADER,
    EventSpool,
    FlightRecorder,
    IncidentManager,
    Registry,
    ServeMetrics,
    adopt_trace,
    format_trace_header,
    get_registry,
    new_trace_id,
    spool_path,
)
from shellac_tpu.utils.failure import Heartbeat, RestartBudget

#: Replica roles for disaggregated serving. The role is ADVISORY for
#: the tier's pair scheduler — any role still serves the full API, so
#: monolithic fallback always has somewhere to land — but it is
#: surfaced everywhere (/health, /stats, /metrics, `top`) so routing
#: decisions are inspectable.
ROLES = ("monolith", "prefill", "decode")

#: Sentinel distinguishing "prefill_chunk never tuned" from "tuned to
#: None (whole prompts won the sweep)".
_UNTUNED = object()


def _render_plp(plp):
    """Prompt logprobs for a response: position 0 has no predictor and
    renders as null (the OpenAI convention); one definition so the
    n==1, best_of, and streaming shapes cannot drift."""
    return [None] + plp[1:]


def retry_after(lo: float, hi: float) -> float:
    """A Retry-After value drawn uniformly from [lo, hi]. Every 503/429
    this server emits goes through here: a fixed interval would tell
    every rejected client to come back at the SAME instant, and a
    recovering or draining replica would eat a synchronized thundering
    herd exactly when it is least able to absorb one. The bounds span
    multiple whole seconds because the HTTP header is rendered as
    integer delta-seconds — sub-second jitter would round away."""
    return random.uniform(lo, hi)


class ProfileInProgress(RuntimeError):
    """POST /debug/profile while a capture is already running: the
    profiler is process-global state, so captures are strictly one at
    a time (HTTP 409, not a queue — the second caller retries after
    the first capture's window elapses)."""


class ServerUnavailable(RuntimeError):
    """The server pushed back instead of serving: over the pending cap
    (HTTP 429), mid-recovery, or a request shed on an expired deadline
    (both HTTP 503). A RuntimeError subclass so programmatic callers
    that only know the old fatal contract still fail loudly; the HTTP
    layer maps it to the right status plus a Retry-After header
    instead of a generic 500."""

    def __init__(self, msg: str, *, http_status: int = 503,
                 retry_after: float = 1.0):
        super().__init__(msg)
        self.http_status = http_status
        self.retry_after = retry_after


class _Generation:
    """One scheduler-thread + engine incarnation.

    The supervisor replaces the WHOLE object on recovery: a wedged
    scheduler thread keeps references to its own engine, submit queue,
    and stop event, so it can never consume a successor's work — and
    `dead` / the identity check against the server's current generation
    make any results it produces after un-wedging discardable."""

    __slots__ = ("gen", "engine", "submit_q", "stop", "step_started",
                 "thread", "dead")

    def __init__(self, gen: int, engine):
        self.gen = gen
        self.engine = engine
        self.submit_q: queue.Queue = queue.Queue()
        self.stop = threading.Event()
        # Wall-clock (monotonic) start of the engine step in flight,
        # None between steps; the watchdog reads it cross-thread.
        self.step_started: Optional[float] = None
        self.thread: Optional[threading.Thread] = None
        # Set (under the server lock) the moment the supervisor starts
        # replacing this generation; admission and the watchdog treat a
        # dead generation as already gone.
        self.dead = False


class _ImportAck:
    """Cross-thread ack for one POST /kv/import: the handler thread
    blocks on `event` while the scheduler (the engine-owning thread)
    performs the import."""

    __slots__ = ("event", "slot", "error", "retryable")

    def __init__(self):
        self.event = threading.Event()
        self.slot: Optional[int] = None
        self.error: Optional[str] = None
        self.retryable = False

    def ok(self, slot: int) -> None:
        self.slot = slot
        self.event.set()

    def fail(self, msg: str, retryable: bool) -> None:
        self.error = msg
        self.retryable = retryable
        self.event.set()


class _Pending:
    __slots__ = ("event", "result", "error", "chunks", "emitted", "holdback",
                 "lps", "plp", "tlp", "rid", "deadline", "kind", "trace",
                 "tenant", "on_finish")

    def __init__(self, rid, stream: bool = False, holdback: int = 0,
                 deadline: Optional[float] = None, trace=None,
                 tenant: Optional[str] = None):
        self.rid = rid
        # Tenant id the request carried (None when untenanted):
        # surfaces in /debug/requests and labels the QoS counters.
        self.tenant = tenant
        # Settlement hook, invoked exactly once by finish() — the one
        # choke point every settle path (finish/shed/cancel/fault/
        # sweep) already goes through. Releases the tenant's
        # concurrency lease, so a request that dies on ANY path frees
        # its admission slot.
        self.on_finish: Optional[Callable[[], None]] = None
        # Observability span (obs.RequestTrace): created at admission,
        # handed to the engine for the prefill/first-token marks, and
        # settled wherever the request settles (finish/shed/abort).
        self.trace = trace
        # Absolute monotonic deadline mirroring the client's timeout;
        # the scheduler sheds the request if this expires before its
        # prefill ever runs (None = no deadline).
        self.deadline = deadline
        # How the error in `error` should surface: "error" (bad
        # request, ValueError/400), "fault" (server fault,
        # RuntimeError/500), "shed" (expired deadline under
        # saturation, ServerUnavailable/503 — retryable, unlike 400).
        self.kind = "error"
        self.event = threading.Event()
        self.result = None
        self.error: Optional[str] = None
        # Streaming requests also get a chunk queue: lists of newly
        # generated token ids, then a None sentinel at completion.
        self.chunks: Optional[queue.Queue] = queue.Queue() if stream else None
        self.emitted = 0
        # Tokens withheld from deltas: a stop-sequence match truncates
        # up to max(len(stop)) tokens at the end, so anything closer to
        # the tail than that may still disappear.
        self.holdback = holdback
        # Per-token logprobs of the final result (engines built with
        # logprobs=True deposit them at completion).
        self.lps = None
        self.plp = None  # prompt per-token logprobs (prompt_logprobs)
        self.tlp = None  # per-token top-K alternatives ((ids, lps) pairs)

    def finish(self):
        cb, self.on_finish = self.on_finish, None
        if cb is not None:
            cb()
        if self.chunks is not None:
            self.chunks.put(None)
        self.event.set()


class InferenceServer:
    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        tokenizer=None,
        engine: Optional[BatchingEngine] = None,
        model_name: str = "shellac_tpu",
        step_timeout: Optional[float] = None,
        max_pending: Optional[int] = None,
        restart_budget: int = 0,
        restart_window: float = 300.0,
        engine_factory: Optional[Callable[[], Any]] = None,
        heartbeat_path: Optional[str] = None,
        registry: Optional[Registry] = None,
        metrics: bool = True,
        autotune: bool = False,
        debug: bool = True,
        debug_include_text: bool = False,
        profile_dir: Optional[str] = None,
        recorder: Optional[FlightRecorder] = None,
        role: str = "monolith",
        adopt_ttl: float = 120.0,
        spool_dir: Optional[str] = None,
        spool_max_bytes: int = 8 << 20,
        incident_dir: Optional[str] = None,
        incident_rate: int = 6,
        incident_window: float = 600.0,
        incident_retention: int = 24,
        incident_capture_seconds: float = 0.0,
        park_dir: Optional[str] = None,
        park_max_bytes: int = 256 << 20,
        tenant_config: Optional[Any] = None,
        preempt_after: Optional[float] = None,
        **engine_kw,
    ):
        if role not in ROLES:
            raise ValueError(f"role={role!r}; have {ROLES}")
        #: Disaggregated-serving role (serve --role). Advisory: the
        #: tier pairs prefill/decode replicas by it; the full API
        #: stays served whatever the role, so monolithic fallback and
        #: mixed fleets always work.
        self.role = role
        # Observability: every span/counter lands in `registry` — the
        # process-global default unless the caller isolates one.
        # metrics=False swaps in a disabled registry (all writes no-op,
        # /metrics answers 404) without any call-site branching.
        if registry is None:
            registry = get_registry() if metrics else Registry(enabled=False)
        self._registry = registry
        self._m = ServeMetrics(registry)
        # Introspection: the flight recorder feeds /debug/requests and
        # /debug/request/<trace_id>. debug=False (serve --no-debug)
        # 404s the endpoints AND disables recording; text redaction is
        # separate — events and the in-flight table carry prompt or
        # generated text only with debug_include_text (serve
        # --debug-include-text).
        self._debug = bool(debug)
        self._debug_text = bool(debug_include_text)
        # Durable event spool (serve --spool-dir): the recorder's ring
        # also spills to a rotating on-disk JSONL file, so a SIGKILL'd
        # replica's in-flight timelines survive to disk (recovered via
        # `top --trace <id> --spool <dir>` or read_spool). PR 10
        # redaction applies on the way to disk unless
        # --debug-include-text opted in.
        self._spool = (
            EventSpool(spool_path(spool_dir),
                       max_bytes=spool_max_bytes,
                       include_text=self._debug_text)
            if spool_dir and self._debug else None
        )
        self._recorder = (recorder if recorder is not None
                          else FlightRecorder(registry=registry,
                                              enabled=self._debug,
                                              spool=self._spool))
        # On-demand profiling (POST /debug/profile?seconds=N): writes
        # jax.profiler traces under profile_dir; the non-blocking lock
        # guards the process-global profiler — one capture at a time.
        self._profile_dir = profile_dir
        self._profile_lock = threading.Lock()
        # Incident black box (serve --incident-dir): trigger-driven
        # evidence bundles — supervisor wedge→rebuild / scheduler
        # death / restart-budget exhaustion fire automatically, and
        # POST /debug/incident fires manually. Sections are evaluated
        # AT TRIGGER TIME; a page-style trigger may also arm a bounded
        # jax.profiler capture through the same one-at-a-time profile
        # lock the /debug/profile endpoint uses.
        self._incidents: Optional[IncidentManager] = None
        if incident_dir and self._debug:
            self._incidents = IncidentManager(
                incident_dir,
                source="server",
                registry=registry,
                recorder=self._recorder,
                sections={
                    "flight_recorder": lambda: self._recorder.tail(
                        self._recorder.capacity),
                    "metrics": self._registry.snapshot,
                    "requests": self.debug_requests,
                    "latency": self.latency_summary,
                    "step_phases": self._step_phase_digest,
                    "config": self._config_fingerprint,
                },
                rate=incident_rate,
                rate_window=incident_window,
                retention=incident_retention,
                capture_fn=(self.profile if profile_dir else None),
                capture_seconds=incident_capture_seconds,
                analyze_fn=self._analyze_capture,
            )
        self._t0 = time.monotonic()
        # Validate BEFORE starting the scheduler thread: raising after
        # start() would orphan an engine-owning daemon thread the
        # caller can never close().
        #
        # step_timeout arms the wedge watchdog. A follower process
        # dying mid-collective leaves the primary's step() WEDGED in
        # native code — no exception ever surfaces, so the scheduler-
        # death path alone cannot save pending requests. The watchdog
        # detects the stall from outside and hands the generation to
        # the supervisor. serve --step-timeout wires this; single-host
        # deployments usually leave it off (a long prefill compile
        # would trip a short timeout).
        if step_timeout is not None and step_timeout <= 0:
            raise ValueError("step_timeout must be > 0 seconds")
        if max_pending is not None and max_pending < 1:
            raise ValueError("max_pending must be >= 1")
        if restart_budget < 0:
            raise ValueError("restart_budget must be >= 0")
        if restart_budget > 0 and engine is not None and engine_factory is None:
            raise ValueError(
                "restart_budget > 0 with a prebuilt engine needs an "
                "engine_factory: the server cannot rebuild an engine "
                "it did not construct"
            )
        if engine is None:
            # Engines this server builds share its registry, so engine
            # gauges and request spans expose through one scrape (and a
            # supervisor-rebuilt engine keeps depositing there too).
            engine_kw.setdefault("registry", registry)
            engine = BatchingEngine(cfg, params, **engine_kw)
            if engine_factory is None:
                # Retained cfg/params/engine_kw rebuild an identical
                # engine on recovery; params are shared with the dead
                # engine, which is safe — jax arrays are immutable.
                engine_factory = functools.partial(
                    BatchingEngine, cfg, params, **engine_kw
                )
        self.model_name = model_name
        self.tokenizer = tokenizer
        self._constraint_cache: "OrderedDict[str, Any]" = OrderedDict()
        self._pending: Dict[int, _Pending] = {}
        self._ids = itertools.count()
        # Serializes admission against the supervisor's generation swap
        # and pending sweep: a request either lands in _pending before
        # the sweep (and is failed loudly by it) or sees the post-swap
        # state checks. Never held across an engine step.
        self._lock = threading.Lock()
        self._closed = threading.Event()
        self._fatal: Optional[str] = None
        self._recovering = False
        # Graceful drain: admission refused (503 + Retry-After),
        # readiness flipped, in-flight requests run to completion. A
        # router polling /health bleeds traffic off, and once
        # `pending` reaches zero the replica can exit with zero drops.
        self._draining = False
        self.step_timeout = step_timeout
        self.max_pending = max_pending
        self._engine_factory = engine_factory
        self._budget = (
            RestartBudget(restart_budget, restart_window)
            if restart_budget > 0 and engine_factory is not None else None
        )
        self.restarts = 0   # generations rebuilt by the supervisor
        self.shed = 0       # requests shed on an expired deadline
        # One-way flag letting the per-step shed sweep early-out in
        # O(1) while NO request has ever carried a deadline (the
        # common all-default-timeout deployment). Deliberately never
        # reset — a stale True only costs the scan, a wrong False
        # would stop shedding.
        self._saw_deadline = False
        # KV migration (disaggregated serving). Prefill side: rid ->
        # decode-replica URL for in-flight prefill_only requests (the
        # scheduler exports the frozen slot and a push worker ships
        # it). Decode side: migration id -> (_Pending, import time) —
        # imported requests decode immediately and the adopt request
        # attaches to the pending; unadopted entries expire after
        # adopt_ttl so an abandoned migration cannot pin results
        # forever.
        self._migrate_targets: Dict[int, str] = {}
        self._adoptions: Dict[str, Tuple[_Pending, float]] = {}
        self._adopt_ttl = float(adopt_ttl)
        # KV park spool (serve --park-dir): frozen slots exported to a
        # durable directory so a parked session survives this replica
        # and resumes on any replica that mounts the same spool.
        self._park = (fabric.KVParkStore(park_dir, park_max_bytes)
                      if park_dir else None)
        self._push_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = None
        # Multi-tenant QoS (serve --tenant-config / --preempt-after).
        # The policy parses BEFORE the scheduler thread starts, so a
        # malformed config is a construction-time ValueError, not a
        # mystery 500 later. Without a config there is no admission
        # controller and no per-tenant gating — tenant ids still ride
        # traces/metrics, but behavior is bit-identical to before.
        self._tenant_policy = (TenantPolicy.parse(tenant_config)
                               if tenant_config is not None else None)
        self._qos_admission = (AdmissionController(self._tenant_policy)
                               if self._tenant_policy is not None
                               else None)
        if preempt_after is not None and preempt_after <= 0:
            raise ValueError("preempt_after must be > 0 seconds")
        self._preempt_after = preempt_after
        # Preempted victims awaiting resume: rid -> (MigrationBlob,
        # tenant, trace_id). Scheduler-thread-only. The blob stays
        # in-memory (resume is same-replica and latency-sensitive); a
        # safety copy goes to the park spool asynchronously when one
        # is configured, so a SIGKILL mid-park still leaves the fleet
        # a resumable artifact.
        self._preempted: "OrderedDict[int, Tuple[Any, Optional[str], str, int]]" = (
            OrderedDict()
        )
        # Parked preempted bytes by resolved tenant (scheduler-thread
        # bookkeeping behind the shellac_tenant_parked_bytes gauge).
        self._parked_tenant_bytes: Dict[str, int] = {}
        # Startup auto-tune (serve --decode-ticks auto, the CLI
        # default): sweep decode_ticks against the live engine BEFORE
        # the scheduler thread exists (the engine is single-owner
        # here), write the winner back, and remember it so supervisor-
        # rebuilt generations inherit the tuned value instead of
        # re-paying the sweep mid-recovery. Library-built servers keep
        # autotune=False: tests and embedders want deterministic, cheap
        # construction.
        self._tuned_ticks: Optional[int] = None
        # prefill_chunk startup sweep (serve --prefill-chunk auto):
        # same discipline — tuned pre-thread, remembered so rebuilt
        # generations inherit it. The sentinel distinguishes "never
        # tuned" from "tuned to None (whole prompts)".
        self._tuned_chunk: Any = _UNTUNED
        if autotune:
            from shellac_tpu.inference.autotune import (
                maybe_autotune,
                maybe_autotune_prefill_chunk,
            )

            res = maybe_autotune(engine)
            if res is not None:
                self._tuned_ticks = res.best
            cres = maybe_autotune_prefill_chunk(engine)
            if cres is not None:
                self._tuned_chunk = cres.best
        # Liveness file beaten from the scheduler loop, so external
        # watchdogs cover inference the same way they cover training.
        # The step watchdog co-beats it while in-process recovery is
        # still possible (and stops once fatal), so an external
        # watchdog doesn't kill the pod mid-wedge-detection or
        # mid-rebuild, defeating the supervisor. Two beaters need the
        # lock: interleaved writes to the shared tmp file would
        # publish a corrupt (= stale-looking) heartbeat.
        self._hb = Heartbeat(heartbeat_path) if heartbeat_path else None
        self._hb_last = 0.0
        self._hb_lock = threading.Lock()
        self._g = self._start_generation(0, engine)
        self._g.thread.start()
        if step_timeout is not None:
            threading.Thread(target=self._watchdog, daemon=True).start()

    # The engine and scheduler thread of the CURRENT generation.
    # Properties (not plain attributes) so every reader — /stats,
    # tests, the OpenAI facade — always sees the live engine, never a
    # wedged predecessor.
    @property
    def engine(self):
        return self._g.engine

    @property
    def _thread(self) -> threading.Thread:
        return self._g.thread

    @property
    def status(self) -> str:
        """Supervisor state: "ok" | "recovering" | "draining" |
        "failed". Failure states win over a drain: a drained-then-
        wedged replica must report the wedge, not a clean drain."""
        if self._fatal is not None:
            return "failed"
        if self._recovering or self._g.dead:
            return "recovering"
        if self._draining:
            return "draining"
        return "ok"

    def health(self) -> Dict[str, Any]:
        """Readiness snapshot served at /health. All reads are plain
        ints/strings — possibly stale, never torn."""
        g = self._g
        info: Dict[str, Any] = {
            "status": self.status,
            "ok": self.status == "ok",
            "role": self.role,
            "pending": len(self._pending),
            "queue_depth": g.submit_q.qsize(),
            "engine_pending": g.engine.pending,
            "generation": g.gen,
            "restarts": self.restarts,
            "restart_budget_used": (self._budget.used
                                    if self._budget is not None else None),
            "shed": self.shed,
            "max_pending": self.max_pending,
            "draining": self._draining,
        }
        if self._fatal is not None:
            info["error"] = self._fatal
        return info

    # ---- graceful drain ---------------------------------------------

    def drain(self) -> Dict[str, Any]:
        """Begin a graceful drain: flip readiness (/health answers 503
        "draining"), refuse new admissions with 503 + Retry-After, and
        let every in-flight request run to completion. Idempotent; the
        returned health snapshot carries `pending`, which a caller (or
        the tier router) polls to zero before stopping the replica —
        that ordering is what makes a planned redeploy drop nothing."""
        with self._lock:
            self._draining = True
            self._m.draining.set(1)
        return self.health()

    def resume_admission(self) -> Dict[str, Any]:
        """Cancel a drain (planned redeploy aborted): readmit traffic.
        A no-op on a fatal server — undraining cannot resurrect it."""
        with self._lock:
            self._draining = False
            self._m.draining.set(0)
        return self.health()

    @property
    def draining(self) -> bool:
        return self._draining

    # ---- observability ----------------------------------------------

    @property
    def metrics_enabled(self) -> bool:
        return self._registry.enabled

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._t0

    def metrics_text(self) -> str:
        """Prometheus text exposition of the shared registry, refreshed
        with scrape-time gauges (engine stats counters, supervisor
        state, uptime). Event-driven series (spans, restart/shed/reject
        counters) are already up to date; only mirrors of host ints are
        set here, so an idle server pays nothing between scrapes. Keeps
        answering through an outage, like /stats."""
        m = self._m
        g = self._g
        for k, v in g.engine.stats.items():
            if isinstance(v, (int, float)):
                m.engine_stat(k).set(v)
        m.cache_backend_info.labels(
            backend=str(g.engine.stats.get("cache_backend", "dense"))
        ).set(1)
        m.role_info.labels(role=self.role).set(1)
        m.generation.set(g.gen)
        m.uptime.set(self.uptime_s)
        m.pending.set(len(self._pending))
        return self._registry.render()

    def qos_snapshot(self) -> Dict[str, Any]:
        """Multi-tenant QoS state for /stats and `top`: per-tenant
        admission counters, the weighted-fair queue's per-class
        depths, and parked preemption state. Cheap host reads only —
        possibly stale, never torn, never a device sync."""
        out: Dict[str, Any] = {}
        if self._qos_admission is not None:
            out["tenants"] = self._qos_admission.snapshot()
        q = getattr(self._g.engine, "_queue", None)
        if hasattr(q, "depths"):
            out["queue_depths"] = {
                CLASS_NAMES.get(k, str(k)): v
                for k, v in q.depths().items()
            }
        if self._preempt_after is not None:
            out["preempt_after_s"] = self._preempt_after
            out["parked_victims"] = len(self._preempted)
            out["parked_bytes"] = dict(self._parked_tenant_bytes)
        return out

    def latency_summary(self) -> Dict[str, Any]:
        """p50/p90/p99 digests (seconds) of the request-span histograms
        for /stats — derived from the same series /metrics exposes, so
        the two surfaces cannot disagree."""
        return {
            "ttft_s": self._m.ttft.summary(),
            "e2e_s": self._m.e2e.summary(),
            "queue_wait_s": self._m.queue_wait.summary(),
        }

    # ---- debug introspection (flight recorder + profiler) -----------

    @property
    def debug_enabled(self) -> bool:
        return self._debug

    @property
    def recorder(self) -> FlightRecorder:
        return self._recorder

    def debug_requests(self) -> Dict[str, Any]:
        """The GET /debug/requests snapshot: the in-flight table (slot
        assignments, per-request state), the overlap window depth, the
        cache backend's per-slot residency(), histogram exemplars, and
        the recorder's ring stats. All reads are cross-thread snapshots
        of host state — possibly stale, never torn, never a device
        sync. Prompt/generated text appears only under
        --debug-include-text (redaction by default)."""
        g = self._g
        eng = g.engine
        slots = list(getattr(eng, "_slots", ()) or ())
        prefilling = set(getattr(eng, "_prefilling", ()) or ())
        slot_of = {req.rid: i for i, req in enumerate(slots)
                   if req is not None}
        now = time.monotonic()
        rows = []
        for rid, p in list(self._pending.items()):
            t = p.trace
            slot = slot_of.get(rid)
            row: Dict[str, Any] = {
                "rid": rid,
                "trace_id": getattr(t, "trace_id", None),
                "slot": slot,
                "state": ("parked" if rid in self._preempted
                          else "queued" if slot is None
                          else "prefilling" if slot in prefilling
                          else "decoding"),
                "tenant": p.tenant,
                "stream": p.chunks is not None,
                "age_s": (round(now - t.t_submit, 3)
                          if t is not None else None),
                "deadline_in_s": (round(p.deadline - now, 3)
                                  if p.deadline is not None else None),
            }
            req = slots[slot] if slot is not None else None
            if req is not None and req.rid == rid:
                row["tokens_out"] = len(req.out)
                if self._debug_text:
                    row["prompt_text"] = (
                        self.tokenizer.decode(
                            [int(x) for x in req.tokens[:256]])
                        if self.tokenizer is not None
                        else [int(x) for x in req.tokens[:256]]
                    )
                    row["output_text"] = (
                        self.tokenizer.decode(list(req.out))
                        if self.tokenizer is not None else list(req.out)
                    )
            rows.append(row)
        out: Dict[str, Any] = {
            "in_flight": rows,
            "pending": len(self._pending),
            "overlap_window_depth": len(getattr(eng, "_windows", ())
                                        or ()),
            "generation": g.gen,
            "recorder": self._recorder.stats(),
            "exemplars": {
                "ttft": self._m.ttft.bucket_exemplars(),
                "e2e": self._m.e2e.bucket_exemplars(),
                "queue_wait": self._m.queue_wait.bucket_exemplars(),
                "tpot": self._m.tpot.bucket_exemplars(),
            },
        }
        try:
            out["slots"] = eng.cache_backend.residency()
        except Exception:  # noqa: BLE001 — introspection must not 500
            out["slots"] = None
        if self._spool is not None:
            out["spool"] = self._spool.stats()
        if self._incidents is not None:
            out["last_incident"] = self._incidents.last
        return out

    def debug_request(self, trace_id: str) -> Optional[Dict[str, Any]]:
        """The GET /debug/request/<trace_id> timeline, or None for an
        id the ring no longer (or never) holds. When the ring has
        evicted the id but a spool is configured, the on-disk copy
        answers instead — the same recovery path `top --spool` uses
        on a dead replica, available while the replica still lives."""
        events = self._recorder.events_for(trace_id)
        source = "ring"
        if not events and self._spool is not None:
            events = self._spool.events_for(trace_id)
            source = "spool"
        if not events:
            return None
        return {"trace_id": trace_id, "events": events,
                "source": source}

    def profile(self, seconds: float) -> Dict[str, Any]:
        """POST /debug/profile?seconds=N: capture a jax.profiler device
        trace of the LIVE engine for `seconds`, written under
        --profile-dir. The handler thread sleeps through the window
        (the scheduler keeps serving); the profiler is process-global,
        so captures are strictly one at a time (ProfileInProgress ->
        HTTP 409)."""
        if self._profile_dir is None:
            raise ValueError(
                "profiling needs serve --profile-dir (no capture "
                "directory configured)"
            )
        seconds = float(seconds)
        if not 0 < seconds <= 120:
            raise ValueError(
                f"seconds={seconds:g} out of range (0, 120]"
            )
        if not self._profile_lock.acquire(blocking=False):
            raise ProfileInProgress(
                "a profiler capture is already running; retry after "
                "its window elapses"
            )
        try:
            import jax

            path = os.path.join(
                self._profile_dir,
                f"trace-{int(time.time() * 1000)}",
            )
            jax.profiler.start_trace(path)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            n_files = sum(
                len(files) for _, _, files in os.walk(path)
            )
            self._recorder.record(None, "profile-capture", src="server",
                                  seconds=seconds, trace_dir=path,
                                  files=n_files)
            # capture_id is the path component trace-report resolves:
            # `python -m shellac_tpu trace-report <trace_dir>` works
            # verbatim on the returned value.
            return {"trace_dir": path,
                    "capture_id": os.path.basename(path),
                    "seconds": seconds, "files": n_files}
        finally:
            self._profile_lock.release()

    @staticmethod
    def _analyze_capture(trace_dir: str) -> Dict[str, Any]:
        """trace-report analysis of one capture directory (the
        ?report=1 inline payload and the bundle's trace_report.json)."""
        from shellac_tpu.obs import tracereport

        return tracereport.analyze(trace_dir)

    # ---- incident black box ------------------------------------------

    @property
    def incidents(self) -> Optional[IncidentManager]:
        return self._incidents

    @property
    def spool(self) -> Optional[EventSpool]:
        return self._spool

    def _step_phase_digest(self) -> Dict[str, Any]:
        """Per-phase step-time digest (sum/count/share) from the
        shellac_step_phase_seconds histograms — the bundle's answer to
        'where was the engine tick going when this fired'."""
        phases: Dict[str, Any] = {}
        total = 0.0
        from shellac_tpu.obs import STEP_PHASES

        for phase in STEP_PHASES:
            h = self._registry.get("shellac_step_phase_seconds",
                                   phase=phase)
            if h is None:
                continue
            phases[phase] = {"sum_s": round(h.sum, 6),
                             "count": h.count,
                             "p50_ms": (round(1e3 * (h.percentile(0.5)
                                                     or 0.0), 3))}
            total += h.sum
        for row in phases.values():
            row["share"] = (round(row["sum_s"] / total, 4)
                            if total > 0 else 0.0)
        return phases

    def _config_fingerprint(self) -> Dict[str, Any]:
        """Config + engine/mesh identity: enough to answer 'what
        exactly was running' from the bundle alone."""
        import dataclasses

        g = self._g
        eng = g.engine
        cfg = getattr(eng, "cfg", None)
        try:
            cfg_d = dataclasses.asdict(cfg) if cfg is not None else None
        except TypeError:
            cfg_d = str(cfg)
        mesh = getattr(eng, "mesh", None)
        return {
            "model": self.model_name,
            "role": self.role,
            "generation": g.gen,
            "restarts": self.restarts,
            "status": self.status,
            "uptime_s": round(self.uptime_s, 3),
            "config": cfg_d,
            "engine": {
                "class": type(eng).__name__,
                "n_slots": getattr(eng, "n_slots", None),
                "cache_backend": str(
                    eng.stats.get("cache_backend", "dense")
                    if hasattr(eng, "stats") else None),
                "decode_ticks": getattr(eng, "decode_ticks", None),
                "decode_ticks_source": getattr(
                    eng, "decode_ticks_source", None),
                "overlap_decode": bool(
                    getattr(eng, "overlap_decode", False)),
                "overlap_prefill": bool(
                    getattr(eng, "overlap_prefill", False)),
                "prefill_chunk": getattr(eng, "prefill_chunk", None),
                "prefill_chunk_source": getattr(
                    eng, "prefill_chunk_source", None),
            },
            "mesh": (str(dict(mesh.shape)) if mesh is not None
                     else None),
            "spool": (self._spool.stats()
                      if self._spool is not None else None),
        }

    def trigger_incident(self, trigger: str, *,
                         trace_id: Optional[str] = None,
                         detail: Optional[Dict[str, Any]] = None,
                         capture_seconds: Optional[float] = None,
                         ) -> Optional[str]:
        """Fire one incident trigger (no-op returning None when no
        --incident-dir is configured; None also means rate-limited)."""
        if self._incidents is None:
            return None
        return self._incidents.trigger(
            trigger, trace_id=trace_id, detail=detail,
            capture_seconds=capture_seconds,
        )

    # ---- supervisor --------------------------------------------------

    def _start_generation(self, gen: int, engine) -> _Generation:
        g = _Generation(gen, engine)
        g.thread = threading.Thread(
            target=self._loop, args=(g,), daemon=True,
            name=f"shellac-scheduler-gen{gen}",
        )
        return g

    def _fail_pending_locked(self, msg: str) -> None:
        """Fail every pending request loudly and drain the current
        generation's submit queue (caller holds the lock, so no new
        pending can land mid-sweep). Settlement is arbitrated by the
        atomic dict pop: a scheduler racing this sweep (close() with a
        step still finishing) pops each rid before delivering, so a
        pending this loop can still pop is guaranteed unsettled — a
        just-completed result is never clobbered with an error."""
        while self._pending:
            _, p = self._pending.popitem()
            p.error = msg
            p.kind = "fault"
            if p.trace is not None:
                p.trace.abort("fault")
            p.finish()
        # Every pending just failed; no prefill_only request can reach
        # the export path anymore, so their targets must not outlive
        # them (rids are never reused — a leak would be permanent).
        self._migrate_targets.clear()
        while True:
            try:
                self._g.submit_q.get_nowait()
            except queue.Empty:
                break

    def _recover(self, g: _Generation, msg: str,
                 wedged: bool = False) -> None:
        """Supervisor transition out of a dead/wedged generation:
        fail everything in flight loudly (the unchanged part of the
        contract), then either rebuild a fresh engine under a new
        generation and resume serving, or — restart budget exhausted,
        no factory, in-place factory on a wedge, or server closing —
        stay fatal. Called from the watchdog (wedge) or the dying
        scheduler thread itself (exception); idempotent per generation.

        Memory note: the abandoned thread's frames keep the old
        engine's device allocations (KV cache, executables) alive for
        as long as it stays wedged, so a REBUILD needs headroom for a
        second engine. Size the cache/pool with that in mind, or leave
        restart_budget=0 on memory-tight single-host deployments."""
        # Incident trigger decided under the lock, FIRED after it
        # drops: the bundle write snapshots the recorder/metrics/
        # in-flight state and must not extend the admission-serializing
        # critical section.
        incident: Optional[Tuple[str, Dict[str, Any]]] = None
        with self._lock:
            if g.dead or g is not self._g:
                return  # this generation is already being replaced
            g.dead = True
            g.stop.set()  # a wedged thread that ever returns exits
            self._fail_pending_locked(msg)
            # An IN-PLACE factory (a bound method of the current
            # engine, e.g. MultihostEngine.resync) mutates and reuses
            # the engine the wedged thread is still stepping — two
            # threads would then race one engine and its command
            # broadcasts. Safe after scheduler DEATH (that thread has
            # left the engine); terminal on a WEDGE.
            in_place = (self._engine_factory is not None
                        and getattr(self._engine_factory, "__self__",
                                    None) is g.engine)
            if wedged and in_place:
                # Supervisor state (_fatal/_recovering/restarts/_g) is
                # written under self._lock but read lock-free by the
                # /health and status() snapshot paths — single
                # reference/int swaps, "possibly stale, never torn"
                # (see health()). Annotated rather than locked so the
                # readiness probe never queues behind a recovery.
                self._fatal = (  # shellac: ignore[SH010]
                    f"{msg} [in-place resync cannot recover a wedged "
                    "step: the stuck thread still owns the engine — "
                    "restart the pod]"
                )
                # Terminal AND the pod is about to be restarted by
                # hand: if any fatal deserves a bundle (the in-memory
                # evidence dies with the pod), this one does.
                recover = False
                incident = ("wedge-fatal",
                            {"error": self._fatal,
                             "generation": g.gen,
                             "restarts": self.restarts})
            else:
                recover = (self._budget is not None
                           and not self._closed.is_set()
                           and self._budget.allow())
                if not recover:
                    if (self._budget is not None
                            and not self._closed.is_set()):
                        msg += (f" [restart budget exhausted: "
                                f"{self._budget.max_restarts} "
                                f"restart(s) per "
                                f"{self._budget.window:g}s]")
                        incident = ("restart-budget-exhausted",
                                    {"error": msg,
                                     "generation": g.gen,
                                     "restarts": self.restarts})
                    self._fatal = msg
                else:
                    # Lock-free snapshot readers by design — see the
                    # wedge-fatal arm above.
                    self._recovering = True  # shellac: ignore[SH010]
                    self.restarts += 1  # shellac: ignore[SH010]
                    self._m.restarts.inc()
                    incident = (
                        "wedge-rebuild" if wedged
                        else "scheduler-death",
                        {"error": msg, "generation": g.gen,
                         "restarts": self.restarts},
                    )
        if incident is not None:
            # Evidence FIRST (the recorder still holds the fault's
            # events; the rebuild below may take seconds), then the
            # rebuild. Wedge-class and rebuild triggers arm the
            # auto-capture if one was configured
            # (--incident-capture-seconds) — the device state behind
            # a wedge is exactly what a post-mortem wants, most of
            # all on the terminal wedge-fatal arm where the pod
            # restart is about to destroy it. Only budget exhaustion
            # skips it: there is no engine left worth profiling.
            self.trigger_incident(
                incident[0], detail=incident[1],
                capture_seconds=(
                    0 if incident[0] == "restart-budget-exhausted"
                    else None),
            )
        if not recover:
            return
        # Rebuild OUTSIDE the lock: engine construction allocates
        # device memory and may compile, and /health + admission must
        # stay responsive (reporting "recovering") meanwhile. Keep the
        # liveness heartbeat fresh for the whole rebuild — without a
        # step watchdog (no step_timeout) nothing else beats here, and
        # an external watchdog restarting the pod mid-rebuild would
        # defeat the supervisor.
        stop_beat = threading.Event()
        if self._hb is not None:
            def _rebuild_beater():
                while not stop_beat.wait(0.5):
                    self._beat(g)

            threading.Thread(target=_rebuild_beater, daemon=True).start()
        try:
            engine = self._engine_factory()
            if (self._tuned_ticks is not None
                    and getattr(engine, "decode_ticks_requested", None)
                    == "auto"
                    and getattr(engine, "_decode_ticks_tunable", True)):
                # The rebuilt generation inherits the startup tune; a
                # fresh sweep mid-recovery would stretch the outage.
                engine.set_decode_ticks(self._tuned_ticks)
                engine.decode_ticks_source = "auto-tuned"
            if (self._tuned_chunk is not _UNTUNED
                    and getattr(engine, "prefill_chunk_requested", None)
                    == "auto"
                    and getattr(engine, "_decode_ticks_tunable", True)):
                engine.set_prefill_chunk(self._tuned_chunk)
                engine.prefill_chunk_source = "auto-tuned"
        except Exception as e:  # noqa: BLE001 — any rebuild fault is fatal
            with self._lock:
                self._recovering = False
                self._fatal = (f"{msg}; engine rebuild failed: "
                               f"{type(e).__name__}: {e}")
            return
        finally:
            stop_beat.set()
        with self._lock:
            self._recovering = False
            if self._closed.is_set():
                self._fatal = "server closed during recovery"
                return
            # One reference swap; the engine/_thread properties read it
            # lock-free so every reader sees the live generation
            # without queueing behind recovery.
            self._g = self._start_generation(g.gen + 1, engine)  # shellac: ignore[SH010]
            self._g.thread.start()

    def _watchdog(self) -> None:
        """Detect a wedged engine step (lost follower, dead relay) from
        outside the scheduler thread. One watchdog follows the
        supervisor across generations for the server's lifetime; it
        exits when the server closes or goes fatal."""
        poll = min(self.step_timeout / 4, 1.0)
        while not self._closed.wait(poll):
            if self._fatal is not None:
                return
            g = self._g
            # Keep the liveness heartbeat fresh through wedge detection
            # and rebuild: the scheduler loop cannot beat while its
            # step is stuck, and an external watchdog restarting the
            # pod mid-recovery would defeat the supervisor. Beats stop
            # once fatal (above), handing the pod back to the external
            # watchdog exactly when in-process recovery has given up.
            self._beat(g)
            started = g.step_started
            if (g.dead or started is None
                    or time.monotonic() - started <= self.step_timeout):
                continue
            self._recover(
                g,
                f"engine step exceeded step_timeout={self.step_timeout}s "
                "(wedged collective or lost follower)",
                wedged=True,
            )

    # ---- scheduler thread (sole owner of its generation's engine) ---

    def _loop(self, g: _Generation) -> None:
        try:
            self._run(g)
        except BaseException as e:  # noqa: BLE001
            # The scheduler thread is the only consumer; if it dies
            # silently every pending and future request blocks forever.
            # Hand the generation to the supervisor: fail everything
            # loudly, then rebuild within the restart budget.
            self._recover(g, f"scheduler died: {type(e).__name__}: {e}")

    def _beat(self, g: _Generation) -> None:
        """Touch the liveness file at most once a second (from the
        scheduler loop, and from the step watchdog while recovery is
        possible); a full disk must degrade observability, not kill
        serving."""
        if self._hb is None:
            return
        with self._hb_lock:
            now = time.monotonic()
            if now - self._hb_last < 1.0:
                return
            self._hb_last = now
            try:
                self._hb.beat(g.engine.stats.get("engine_steps", 0))
            except OSError:
                pass

    def _shed(self, rid, p: _Pending) -> None:
        """Settle one request as shed (both shed paths share this so
        the accounting and message cannot drift)."""
        if self._pending.pop(rid, None) is None:
            return
        # A shed prefill_only request never reaches the export path:
        # drop its migration target too.
        self._migrate_targets.pop(rid, None)
        # Single-writer: both shed paths run on the scheduler thread,
        # so the bare increment cannot lose updates; /health reads it
        # lock-free ("possibly stale, never torn").
        self.shed += 1  # shellac: ignore[SH010]
        if p.trace is not None:
            p.trace.shed()
        p.error = ("request shed: deadline expired before prefill "
                   "(server saturated past the client timeout)")
        p.kind = "shed"
        p.finish()

    def _shed_expired(self, g: _Generation) -> None:
        """Deadline-aware load shedding: drop engine-QUEUED requests
        whose client deadline already passed — the caller's wait timed
        out, so prefilling the prompt would burn compute on an answer
        nobody is waiting for. Requests already in a slot keep running
        (their compute is sunk; the finish path reclaims the slot)."""
        if not self._saw_deadline:
            return
        now = time.monotonic()
        queued = None
        for rid, p in list(self._pending.items()):
            if p.deadline is None or now <= p.deadline:
                continue
            if queued is None:  # one snapshot per sweep, lazily
                queued = {r.rid for r in g.engine._queue}
            if rid not in queued:
                continue
            g.engine.cancel(rid)
            self._shed(rid, p)

    def _process_item(self, g: _Generation, item) -> None:
        rid, tokens, max_new, stop, samp, deadline = item
        qos = None
        if samp and "_qos" in samp:
            # Tenant identity + scheduling class resolved at admission;
            # popped here so the engine's sampling-kwargs whitelist
            # never sees the marker.
            samp = dict(samp)
            qos = samp.pop("_qos")
        if tokens is None:
            # Cancellation marker: drop queued/in-flight work for an
            # abandoned client request.
            g.engine.cancel(rid)
            self._migrate_targets.pop(rid, None)
            p = self._pending.pop(rid, None)
            if p is not None:
                p.error = "cancelled"
                if p.trace is not None:
                    p.trace.abort("cancelled")
                p.finish()
            return
        if deadline is not None and time.monotonic() > deadline:
            # Expired before it ever reached the engine: shed without
            # spending prefill compute.
            p = self._pending.get(rid)
            if p is not None:
                self._shed(rid, p)
            return
        if samp and "_beam" in samp:
            # Beam request: runs synchronously on the scheduler thread
            # (the engine owner), like a long prefill — the device
            # program IS the whole request, so there is no slot to
            # multiplex.
            self._run_beam(g, rid, tokens, max_new, samp["_beam"])
            return
        if samp and "_kv_import" in samp:
            # KV adoption (decode replica): imported on the scheduler
            # thread — the only thread allowed to touch the engine.
            self._import_item(g, rid, *samp["_kv_import"])
            return
        if samp and "_kv_seed" in samp:
            # Prefix-seed adoption (fabric replication): registers
            # pure cache contents — no pending, no request.
            self._seed_item(g, *samp["_kv_seed"])
            return
        if samp and "_kv_export_chain" in samp:
            # Prefix-chain export (fabric replication, holder side):
            # the handler thread ships the blob; only the device pull
            # runs here.
            self._export_chain_item(g, *samp["_kv_export_chain"])
            return
        extra = {}
        if qos is not None:
            tenant, qcls, qweight = qos
            if tenant is not None:
                extra["tenant"] = tenant
            if qcls is not None:
                extra["qos_class"] = qcls
            if qweight is not None:
                extra["qos_weight"] = qweight
        if samp and "_migrate" in samp:
            # Prefill-only admission (prefill replica): the engine
            # freezes the slot at prefill; _service_frozen exports it
            # and the push worker ships it to the decode target.
            samp = dict(samp)
            self._migrate_targets[rid] = samp.pop("_migrate")
            extra["prefill_only"] = True
        pend = self._pending.get(rid)
        try:
            g.engine.submit(
                rid, tokens, max_new, stop=stop,
                trace=pend.trace if pend is not None else None,
                **extra, **samp,
            )
        except (ValueError, TypeError) as e:
            # TypeError: unknown sampling kwarg from a programmatic
            # caller — a bad request, not a scheduler-killing fault.
            # The pending may already be gone: close()'s sweep can
            # clear _pending while this thread is still draining its
            # last backlog items.
            self._migrate_targets.pop(rid, None)
            p = self._pending.pop(rid, None)
            if p is not None:
                p.error = str(e)
                if p.trace is not None:
                    p.trace.abort("error")
                p.finish()

    def _run_beam(self, g: _Generation, rid, tokens, max_new: int,
                  beam: Dict[str, Any]) -> None:
        """Run one beam-search request on the scheduler thread and
        settle its pending. Engine faults stay request-scoped: a pool-
        exhausted paged beam (RuntimeError) fails THIS request loudly
        instead of killing the scheduler."""
        p = self._pending.get(rid)
        if p is not None and p.trace is not None:
            p.trace.prefill_start()
        try:
            bs = getattr(g.engine, "beam_search", None)
            if bs is None:
                raise ValueError(
                    "beam search is not supported by this engine "
                    "(multi-host serving decodes through slots only)"
                )
            seqs, scores = bs(
                tokens, num_beams=beam["num_beams"],
                max_new_tokens=max_new,
                eos_id=getattr(g.engine, "eos_id", None),
                length_penalty=beam["length_penalty"],
                constraint=beam.get("constraint"),
            )
        except (ValueError, TypeError) as e:
            p = self._pending.pop(rid, None)
            if p is not None:
                p.error = str(e)
                if p.trace is not None:
                    p.trace.abort("error")
                p.finish()
            return
        except Exception as e:  # noqa: BLE001 — request-scoped fault
            p = self._pending.pop(rid, None)
            if p is not None:
                p.error = f"beam search failed: {type(e).__name__}: {e}"
                p.kind = "fault"
                if p.trace is not None:
                    p.trace.abort("fault")
                p.finish()
            return
        p = self._pending.pop(rid, None)
        if p is None:
            return  # cancelled or swept while the search ran
        if p.trace is not None:
            p.trace.first_token()
            p.trace.finish(sum(len(s) for s in seqs))
        p.result = {"beams": seqs, "scores": scores}
        p.finish()

    # ---- KV migration (disaggregated serving) -----------------------

    def _import_item(self, g: _Generation, rid, blob, ack,
                     tid) -> None:
        """Adopt one migrated request into the engine (scheduler
        thread). Failures settle the pending AND the handler's ack —
        PoolExhausted is the retryable class (fresh pair can serve),
        a refused blob (wrong backend/geometry) is a 400. `tid` is
        the migration id import_kv REGISTERED (minted when the blob
        carried none), so failure cleanup always finds the adoption
        entry."""
        pend = self._pending.get(rid)
        try:
            slot = disagg.import_blob(
                g.engine, blob, rid,
                trace=pend.trace if pend is not None else None,
            )
        except PoolExhausted:
            self._fail_import(rid, tid, ack, retryable=True,
                              msg="decode replica has no free slot or "
                                  "pool capacity; retry elsewhere")
            return
        except (ValueError, TypeError) as e:
            self._fail_import(rid, tid, ack, retryable=False, msg=str(e))
            return
        except Exception as e:  # noqa: BLE001 — request-scoped fault
            self._fail_import(
                rid, tid, ack, retryable=True,
                msg=f"kv import failed: {type(e).__name__}: {e}",
            )
            return
        self._m.migrations.labels(outcome="import").inc()
        ack.ok(slot)

    def _fail_import(self, rid, tid, ack, *, retryable: bool,
                     msg: str) -> None:
        self._m.migrations.labels(outcome="import_failed").inc()
        if tid is not None:
            self._adoptions.pop(tid, None)
        p = self._pending.pop(rid, None)
        if p is not None:
            p.error = msg
            if p.trace is not None:
                p.trace.abort("error")
            p.finish()
        ack.fail(msg, retryable)

    def _seed_item(self, g: _Generation, blob, ack, tid) -> None:
        """Adopt one prefix-seed blob (scheduler thread). Unlike
        _import_item there is no pending and no slot — a seed is pure
        cache contents — so failures settle only the handler's ack.
        PoolExhausted is the retryable class; a refused blob (wrong
        kind/backend/geometry) is a 400 with the registry untouched."""
        try:
            n = fabric.seed_chain(g.engine, blob)
        except PoolExhausted:
            self._m.fabric_seed_rejects.labels(reason="exhausted").inc()
            ack.fail(
                "no free-list headroom for the seed (seeding never "
                "evicts to make room); retry after load falls",
                retryable=True,
            )
            return
        except (ValueError, TypeError) as e:
            self._m.fabric_seed_rejects.labels(reason="mismatch").inc()
            ack.fail(str(e), False)
            return
        except Exception as e:  # noqa: BLE001 — request-scoped fault
            self._m.fabric_seed_rejects.labels(reason="fault").inc()
            ack.fail(f"kv seed failed: {type(e).__name__}: {e}", True)
            return
        self._m.fabric_seeded.inc(n)
        if self._recorder is not None:
            self._recorder.record(
                tid, "kv-seed", blocks=n,
                chain=len(blob.header.get("chain") or ()), src="server",
            )
        ack.ok(n)

    def _export_chain_item(self, g: _Generation, tip: bytes, ack,
                           tid) -> None:
        """Export one cached prefix chain (scheduler thread) and hand
        the blob back through the ack; the handler thread owns the
        HTTP leg. An evicted link is a 400 — the tier's directory is
        stale, and re-planning beats retrying a chain that is gone."""
        try:
            blob = fabric.export_chain(g.engine, tip, trace_id=tid)
        except (ValueError, TypeError) as e:
            ack.fail(str(e), False)
            return
        except Exception as e:  # noqa: BLE001 — request-scoped fault
            ack.fail(
                f"chain export failed: {type(e).__name__}: {e}", True,
            )
            return
        ack.ok(blob)

    def _service_frozen(self, g: _Generation) -> None:
        """Prefill-side migration driver, run on the scheduler thread
        after each step: export every newly frozen prefill-only slot
        (one batched device pull each), release the slot immediately
        (the host copy exists), and hand the blob to a push worker —
        the HTTP leg must never block the engine."""
        eng = g.engine
        if not getattr(eng, "frozen_prefills", None):
            return
        for rid in list(eng.frozen_prefills):
            slot = eng.frozen_prefills[rid]
            req = eng._slots[slot]
            target = self._migrate_targets.pop(rid, None)
            p = self._pending.get(rid)
            tid = (p.trace.trace_id
                   if p is not None and p.trace is not None else None)
            try:
                if target is None:
                    raise ValueError(
                        "prefill_only request lost its migrate_to "
                        "target"
                    )
                blob = disagg.export_slot(eng, slot, req, trace_id=tid)
            except Exception as e:  # noqa: BLE001 — request-scoped fault
                eng.release_frozen(rid)
                self._m.migrations.labels(outcome="export_failed").inc()
                pp = self._pending.pop(rid, None)
                if pp is not None:
                    pp.error = (f"kv export failed: "
                                f"{type(e).__name__}: {e}")
                    pp.kind = "fault"
                    if pp.trace is not None:
                        pp.trace.abort("fault")
                    pp.finish()
                continue
            eng.release_frozen(rid)
            eng.stats["kv_exports"] += 1
            if p is not None and p.trace is not None:
                p.trace.record(
                    "kv-export", src="server", rid=rid, slot=slot,
                    tokens=blob.header["length"], target=target,
                    complete=blob.header["complete"],
                )
            if self._push_pool is None:
                self._push_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="shellac-kv-push",
                )
            if target.startswith("park:"):
                # Park leg: the blob goes to the durable spool, not a
                # decode replica — same worker pool, different sink.
                self._push_pool.submit(
                    self._park_blob, rid, blob, target[len("park:"):],
                )
            else:
                self._push_pool.submit(
                    self._push_migration, rid, blob, target,
                    p.deadline if p is not None else None,
                )

    def _push_migration(self, rid, blob, target: str,
                        deadline: Optional[float]) -> None:
        """Push worker: serialize + POST the blob to the decode
        replica's /kv/import, then settle the prefill client's pending
        with the migration ack — or, on any failure, with a retryable
        503 ("kv-push-failed" marker) so the tier re-runs the full
        prefill->migrate path on a fresh pair."""
        p = self._pending.get(rid)
        tid = (p.trace.trace_id
               if p is not None and p.trace is not None else None)
        data = blob.serialize()
        timeout = 30.0
        if deadline is not None:
            timeout = max(1.0, min(timeout,
                                   deadline - time.monotonic()))
        headers = {"Content-Type": "application/octet-stream"}
        if tid is not None:
            headers[TRACE_HEADER] = format_trace_header(tid, 0)
        t0 = time.monotonic()
        try:
            req = urllib.request.Request(
                target.rstrip("/") + "/kv/import", data=data,
                headers=headers,
            )
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                body = json.loads(resp.read() or b"{}")
        except Exception as e:  # noqa: BLE001 — one retryable leg
            self._m.migrations.labels(outcome="export_failed").inc()
            pp = self._pending.pop(rid, None)
            if pp is not None:
                pp.error = (f"kv-push-failed: could not deliver KV to "
                            f"{target}: {type(e).__name__}: {e}")
                pp.kind = "unavailable"
                if pp.trace is not None:
                    pp.trace.abort("fault")
                pp.finish()
            return
        dt = time.monotonic() - t0
        self._m.kv_transfer_seconds.observe(dt, exemplar=tid)
        self._m.kv_transfer_bytes.observe(float(len(data)),
                                          exemplar=tid)
        self._m.migrations.labels(outcome="export").inc()
        pp = self._pending.pop(rid, None)
        if pp is None:
            return  # cancelled or swept while pushing
        n_out = len(blob.header["request"]["out"])
        pp.result = {
            "migrated": True,
            "migration_id": body.get("migration_id") or tid,
            "decode": target.rstrip("/"),
            "complete": bool(blob.header["complete"]),
            "bytes": len(data),
            "transfer_s": round(dt, 6),
            "tokens_out": n_out,
            "prompt_tokens": int(blob.header["length"]),
        }
        if pp.trace is not None:
            pp.trace.finish(n_out)
        pp.finish()

    def _park_blob(self, rid, blob, park_id: str) -> None:
        """Push worker: spool one exported slot durably and settle the
        parking client's pending with the park receipt. A park that
        did not land durably fails loudly — a receipt for a lost blob
        would strand the session."""
        p = self._pending.get(rid)
        tid = (p.trace.trace_id
               if p is not None and p.trace is not None else None)
        data = blob.serialize()
        try:
            self._park.put(park_id, data)
        except OSError as e:
            pp = self._pending.pop(rid, None)
            if pp is not None:
                pp.error = (f"kv park failed: could not spool "
                            f"{park_id!r}: {type(e).__name__}: {e}")
                pp.kind = "fault"
                if pp.trace is not None:
                    pp.trace.abort("fault")
                pp.finish()
            return
        self._m.fabric_parked.inc()
        self._m.fabric_park_bytes.set(
            float(sum(e["bytes"] for e in self._park.list()))
        )
        if self._recorder is not None:
            self._recorder.record(
                tid, "fabric-park", park_id=park_id, bytes=len(data),
                complete=bool(blob.header["complete"]),
            )
        pp = self._pending.pop(rid, None)
        if pp is None:
            return  # cancelled or swept while spooling
        n_out = len(blob.header["request"]["out"])
        pp.result = {
            "parked": True,
            "park_id": park_id,
            "bytes": len(data),
            "complete": bool(blob.header["complete"]),
            "prompt_tokens": int(blob.header["length"]),
            "tokens_out": n_out,
        }
        if pp.trace is not None:
            pp.trace.finish(n_out)
        pp.finish()

    def _sweep_adoptions(self, g: _Generation) -> None:
        """Expire un-adopted migrations (scheduler thread): a decode
        replica must not pin slots or results for a client that never
        arrived (tier died between the migrate and adopt legs)."""
        if not self._adoptions:
            return
        now = time.monotonic()
        for mid, (p, t) in list(self._adoptions.items()):
            if now - t <= self._adopt_ttl:
                continue
            if self._adoptions.pop(mid, None) is None:
                continue
            if not p.event.is_set():
                g.engine.cancel(p.rid)
                pp = self._pending.pop(p.rid, None)
                if pp is not None:
                    pp.error = ("migration never adopted "
                                "(adopt_ttl expired)")
                    if pp.trace is not None:
                        pp.trace.abort("cancelled")
                    pp.finish()

    # ---- preempt-and-park (multi-tenant QoS) ------------------------

    @staticmethod
    def _free_slot_available(engine) -> bool:
        return any(
            r is None and i not in engine._prefilling
            for i, r in enumerate(engine._slots)
        )

    def _maybe_preempt(self, g: _Generation) -> None:
        """Preempt-and-park: when the best-priority waiting request has
        waited past --preempt-after and every slot is busy, freeze the
        cheapest strictly-lower-class victim mid-decode, export its KV,
        and free the slot so the step that follows seats the waiter.
        The victim's client stays attached — _resume_preempted() later
        re-places the KV in a free slot and decoding continues
        token-identical (greedy and seeded sampling both derive their
        keys from position, not a shared stream)."""
        if self._preempt_after is None:
            return
        engine = g.engine
        q = getattr(engine, "_queue", None)
        best = q.best_waiting() if hasattr(q, "best_waiting") else None
        if best is None:
            return
        wcls, head = best
        waited = time.monotonic() - getattr(head, "t_queued", 0.0)
        if waited < self._preempt_after:
            return
        if self._free_slot_available(engine):
            return  # the next step seats the waiter without violence
        victims = [v for v in engine.preemptable() if v[2] > wcls]
        if not victims:
            return
        # Cheapest victim: lowest priority class first, then fewest
        # MEASURED resident KV bytes (bytes_per_token tracks the cache
        # backend, so int8 halves a victim's cost instead of the rule
        # guessing from token counts alone).
        bpt = engine.cache_backend.bytes_per_token()
        vrid, vslot, vcls, vtokens = max(
            victims, key=lambda v: (v[2], -v[3]))
        req = engine._slots[vslot]
        tenant = getattr(req, "tenant", None)
        p = self._pending.get(vrid)
        tid = (p.trace.trace_id
               if p is not None and p.trace is not None else None)
        if tid is None:
            tid = new_trace_id()
        try:
            finished = engine.preempt(vrid)
        except ValueError:
            return  # raced a finish/cancel; nothing to do
        self._deliver_finished(g, finished)
        if vrid not in engine.frozen_decodes:
            return  # the victim finished while the windows drained
        try:
            blob = disagg.export_slot(engine, vslot, req, trace_id=tid)
        except Exception as e:  # noqa: BLE001 — keep the victim alive
            # Export failed: thaw in place. The slot still holds the
            # request, so clearing the freeze resumes decoding exactly
            # where the drain left it — worse fairness beats a lost
            # request.
            engine.frozen_decodes.pop(vrid, None)
            req.frozen = False
            engine._sdone = engine._sdone.at[vslot].set(False)
            if p is not None and p.trace is not None:
                p.trace.record("preempt-failed", src="server", rid=vrid,
                               error=f"{type(e).__name__}: {e}")
            return
        engine.release_frozen(vrid)
        name = tenant or ANONYMOUS
        nbytes = int(vtokens) * int(bpt)
        self._preempted[vrid] = (blob, tenant, tid, nbytes)
        self._m.tenant_preemptions.labels(tenant=name).inc()
        self._parked_tenant_bytes[name] = (
            self._parked_tenant_bytes.get(name, 0) + nbytes)
        self._m.tenant_parked_bytes.labels(tenant=name).set(
            float(self._parked_tenant_bytes[name]))
        if p is not None and p.trace is not None:
            p.trace.record(
                "preempt-park", src="server", rid=vrid, slot=vslot,
                victim_class=int(vcls), waiter_class=int(wcls),
                resident_tokens=int(vtokens), bytes=nbytes,
                tenant=name,
            )
        # Durable safety copy, fire-and-forget: a SIGKILL before the
        # resume still leaves the fleet a crc-checked artifact in the
        # shared park spool. The client's pending is NOT settled here
        # — unlike the `park:` migrate leg, preemption keeps the
        # client attached and invisible except as latency.
        if self._park is not None:
            if self._push_pool is None:
                self._push_pool = concurrent.futures.ThreadPoolExecutor(
                    max_workers=4, thread_name_prefix="shellac-kv-push",
                )
            self._push_pool.submit(
                self._park_safety_copy, blob, "preempt-" + tid)

    def _park_safety_copy(self, blob, park_id: str) -> None:
        """Push worker: best-effort durable copy of a preempted blob.
        The authoritative copy is in-memory and resume does not block
        on the spool, so a failed write costs only the error counter
        the store already keeps."""
        try:
            data = blob.serialize()
            self._park.put(park_id, data)
        except (OSError, ValueError):
            return
        self._m.fabric_parked.inc()
        self._m.fabric_park_bytes.set(
            float(sum(e["bytes"] for e in self._park.list()))
        )

    def _drop_preempted(self, vrid) -> None:
        """Forget one parked victim and settle its parked-bytes
        accounting (resume landed, client vanished, or resume failed
        terminally)."""
        blob, tenant, tid, nbytes = self._preempted.pop(vrid)
        name = tenant or ANONYMOUS
        left = self._parked_tenant_bytes.get(name, 0) - nbytes
        if left > 0:
            self._parked_tenant_bytes[name] = left
        else:
            self._parked_tenant_bytes.pop(name, None)
            left = 0
        self._m.tenant_parked_bytes.labels(tenant=name).set(float(left))

    def _resume_preempted(self, g: _Generation) -> None:
        """Re-place preempted KV into free slots, oldest victim first.
        Runs AFTER the step, so the preempting waiter seats before its
        victim competes for the slot it vacated."""
        if not self._preempted:
            return
        engine = g.engine
        for vrid in list(self._preempted):
            if not self._free_slot_available(engine):
                return
            blob, tenant, tid, _ = self._preempted[vrid]
            p = self._pending.get(vrid)
            if p is None or p.event.is_set():
                # Client gone (cancelled or swept): drop the blob.
                self._drop_preempted(vrid)
                continue
            try:
                slot = disagg.import_blob(engine, blob, vrid, p.trace)
            except PoolExhausted:
                return  # capacity, not corruption: retry next loop
            except Exception as e:  # noqa: BLE001 — request-scoped
                self._drop_preempted(vrid)
                pp = self._pending.pop(vrid, None)
                if pp is not None:
                    pp.error = (f"preempt resume failed: "
                                f"{type(e).__name__}: {e}")
                    pp.kind = "unavailable"
                    if pp.trace is not None:
                        pp.trace.abort("fault")
                    pp.finish()
                continue
            self._drop_preempted(vrid)
            name = tenant or ANONYMOUS
            # The import rebuilds the request with default QoS fields;
            # restore identity so a resumed victim can be preempted
            # (or scheduled) under its own contract again.
            r2 = engine._slots[slot]
            if r2 is not None:
                r2.tenant = tenant
                if self._tenant_policy is not None:
                    spec = self._tenant_policy.spec(name)
                    r2.qos_class = spec.qos_class
                    r2.qos_weight = spec.qos_weight
            if p.trace is not None:
                p.trace.record("preempt-resume", src="server",
                               rid=vrid, slot=slot, tenant=name)

    def _run(self, g: _Generation) -> None:
        engine = g.engine
        # Multi-host engines need a step per loop iteration even when
        # idle: follower processes wait inside the command broadcast,
        # and an un-stepped primary would leave them parked in a device
        # collective until its transport times out.
        idle_steps = bool(getattr(engine, "needs_heartbeat", False))
        while not g.stop.is_set():
            drained = False
            while True:
                try:
                    item = g.submit_q.get_nowait()
                except queue.Empty:
                    break
                drained = True
                self._process_item(g, item)
            self._shed_expired(g)
            self._sweep_adoptions(g)
            self._beat(g)
            if engine.pending or idle_steps:
                # QoS: freeing a victim's slot BEFORE the step lets
                # this very step seat the starved waiter.
                self._maybe_preempt(g)
                g.step_started = time.monotonic()
                try:
                    finished = engine.step() or []
                finally:
                    # Clear the clock even when the step RAISES, so the
                    # watchdog cannot misread a dying scheduler (whose
                    # own _recover is about to run) as a wedge.
                    g.step_started = None
                if g.dead or g is not self._g:
                    # Stale generation: the supervisor replaced this
                    # engine while the step was wedged. Results the old
                    # generation computed are DISCARDED — the pendings
                    # they would resolve were already failed loudly,
                    # and any same-numbered pendings now belong to the
                    # replacement engine.
                    return
                fin = {rid for rid, _ in finished}
                # Stream deltas for requests still in flight. holdback
                # trails the tail by the longest stop length, so a
                # token a later stop match would truncate is never
                # emitted (out only ever shrinks by a matched stop).
                for req in engine._slots:
                    if req is None or req.rid in fin:
                        continue
                    p = self._pending.get(req.rid)
                    if p is None or p.chunks is None:
                        continue
                    upto = max(p.emitted, len(req.out) - p.holdback)
                    if upto > p.emitted:
                        p.chunks.put(list(req.out[p.emitted:upto]))
                        p.emitted = upto
                self._deliver_finished(g, finished)
                # Disaggregated prefill replica: export + ship every
                # slot this step froze (no-op otherwise).
                self._service_frozen(g)
                # Preempted victims re-enter free slots only after the
                # step (the waiter they yielded to seats first).
                self._resume_preempted(g)
                if idle_steps and not drained and not engine.pending:
                    # Idle heartbeat tick: pace the broadcast instead of
                    # spinning the interconnect at full rate.
                    g.stop.wait(0.01)
            elif not drained:
                # Idle: block briefly on the queue instead of spinning.
                # Process in place — re-enqueueing could reorder a
                # submit behind its own cancellation marker.
                try:
                    self._process_item(g, g.submit_q.get(timeout=0.05))
                except queue.Empty:
                    pass

    def _deliver_finished(self, g: _Generation, finished) -> None:
        """Settle every (rid, out) the engine finished this step —
        shared by the step loop and the preemption drain, so the
        logprob-store handoff and pending settlement cannot drift."""
        engine = g.engine
        lp_store = getattr(engine, "finished_logprobs", {})
        plp_store = getattr(engine, "finished_prompt_logprobs", {})
        tl_store = getattr(engine, "finished_top_logprobs", {})
        for rid, out in finished:
            p = self._pending.pop(rid, None)
            if p is not None:
                p.result = out
                if p.trace is not None:
                    p.trace.finish(len(out))
                p.lps = lp_store.pop(rid, None)
                p.plp = plp_store.pop(rid, None)
                p.tlp = tl_store.pop(rid, None)
                if p.chunks is not None and len(out) > p.emitted:
                    p.chunks.put(list(out[p.emitted:]))
                p.finish()
            else:
                lp_store.pop(rid, None)
                plp_store.pop(rid, None)
                tl_store.pop(rid, None)

    # ---- client surface ---------------------------------------------

    def _submit(self, tokens, max_new: int, stop, samp, *, stream: bool,
                deadline: Optional[float] = None,
                trace_ctx: Optional[Tuple[str, int]] = None,
                tenant: Optional[str] = None) -> _Pending:
        # Distributed-trace identity: adopt the (trace_id, attempt) the
        # HTTP layer pulled off x-shellac-trace, minting a fresh id for
        # direct library callers — every admitted request has exactly
        # one id, whoever it came from.
        tid, attempt = (trace_ctx if trace_ctx is not None
                        else (new_trace_id(), 0))
        tenant = str(tenant) if tenant else None
        # The span clock starts at admission, before any copying or
        # queueing, so queue-wait covers everything the client waits
        # through server-side.
        trace = self._m.trace(trace_id=tid, recorder=self._recorder,
                              tenant=tenant)
        # Convert the prompt BEFORE taking the lock: the copy is O(S)
        # and the lock serializes every admission and the supervisor.
        tokens = np.asarray(tokens, np.int32)
        # QoS identity resolved outside the lock too. The priority
        # class/weight come from the tenant policy when one is
        # configured; untenanted servers leave them None and the
        # engine's defaults apply (FIFO-identical scheduling).
        spec = (self._tenant_policy.spec(tenant or ANONYMOUS)
                if self._tenant_policy is not None else None)
        # Admit-event fields built outside the lock too (the optional
        # text decode is O(prompt)); text rides the event only under
        # --debug-include-text.
        admit_fields: Dict[str, Any] = {
            "src": "server", "attempt": attempt,
            "prompt_len": int(tokens.size), "max_new": int(max_new),
            "stream": stream,
        }
        if tenant is not None:
            admit_fields["tenant"] = tenant
        if spec is not None:
            admit_fields["qos_class"] = spec.priority
        if self._debug_text and self.tokenizer is not None:
            admit_fields["prompt_text"] = self.tokenizer.decode(
                [int(t) for t in tokens[:256]]
            )
        with self._lock:
            # Admission control. The lock pairs this with the
            # supervisor's sweep: a request either registers before the
            # sweep (and is failed loudly by it) or sees the post-swap
            # state here — it can never strand in a dead generation's
            # queue unobserved.
            if self._fatal is not None:
                raise RuntimeError(self._fatal)
            if self._closed.is_set():
                raise RuntimeError("server closed")
            g = self._g
            if self._recovering or g.dead:
                self._m.rejects.labels(reason="recovering",
                                       tenant=tenant or "").inc()
                raise ServerUnavailable(
                    "server recovering from an engine fault; retry",
                    http_status=503, retry_after=retry_after(3.0, 8.0),
                )
            if self._draining:
                self._m.rejects.labels(reason="draining",
                                       tenant=tenant or "").inc()
                raise ServerUnavailable(
                    "server draining: not admitting new requests "
                    "(in-flight work is completing); retry elsewhere",
                    http_status=503, retry_after=retry_after(1.0, 4.0),
                )
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                self._m.rejects.labels(reason="overloaded",
                                       tenant=tenant or "").inc()
                raise ServerUnavailable(
                    f"server overloaded: {len(self._pending)} requests "
                    f"pending (max_pending={self.max_pending})",
                    http_status=429, retry_after=retry_after(1.0, 3.0),
                )
            released: Optional[Callable[[], None]] = None
            if self._qos_admission is not None:
                # Per-tenant quotas AFTER the global gates: a global
                # overload answer must not charge a tenant's bucket.
                # Cost = prompt + budgeted max_new — the same token
                # count the engine will reserve, so the bucket meters
                # work, not request count.
                name = tenant or ANONYMOUS
                cost = int(tokens.size) + int(max_new)
                ok, why, wait = self._qos_admission.admit(name, cost)
                if not ok:
                    self._m.rejects.labels(reason="throttled",
                                           tenant=tenant or "").inc()
                    self._m.tenant_throttles.labels(
                        tenant=name, reason=why).inc()
                    # Jittered Retry-After on top of the bucket's
                    # deterministic refill estimate: synchronized
                    # over-quota clients must not return in lockstep.
                    raise ServerUnavailable(
                        f"tenant {name!r} over its {why} quota",
                        http_status=429,
                        retry_after=retry_after(
                            max(wait, 0.5), max(wait, 0.5) + 2.0),
                    )
                self._m.tenant_tokens.labels(tenant=name).inc(cost)
                released = functools.partial(
                    self._qos_admission.release, name)
            rid = next(self._ids)
            holdback = max((len(s) for s in stop), default=0) if stop else 0
            if deadline is not None:
                # Monotonic False->True gate; the scheduler reads it
                # lock-free in _shed_expired as a fast-path skip, and
                # a stale False only delays the first shed sweep one
                # loop iteration.
                self._saw_deadline = True  # shellac: ignore[SH010]
            p = _Pending(rid, stream=stream, holdback=holdback,
                         deadline=deadline, trace=trace, tenant=tenant)
            p.on_finish = released
            self._pending[rid] = p
            samp = dict(samp or {})
            if spec is not None or tenant is not None:
                # Rides the submit tuple to the scheduler thread, which
                # pops it into engine.submit(tenant=, qos_class=,
                # qos_weight=) — the weighted-fair queue's inputs.
                samp["_qos"] = (
                    tenant,
                    spec.qos_class if spec is not None else None,
                    spec.qos_weight if spec is not None else None,
                )
            # Recorded BEFORE the scheduler can see the request: the
            # enqueue below hands it to the engine thread, which
            # records queue/prefill next — admit must already hold the
            # timeline's first seq or a fast scheduler reorders it.
            trace.record("admit", rid=rid, pending=len(self._pending),
                         **admit_fields)
            g.submit_q.put(
                (rid, tokens, max_new, stop, samp, deadline)
            )
        return p

    def _raise(self, p: _Pending):
        # Server faults (scheduler death / wedge / close) are HTTP 500,
        # shed deadlines are saturation — retryable 503 + Retry-After,
        # NOT a 400 an OpenAI SDK would treat as permanent — and
        # anything else is a bad request (400): keep the classes
        # distinct. (A non-streaming caller usually races its own
        # identical timeout and sees that instead; the 503 surfaces
        # when the shed outcome reaches a still-waiting client, e.g.
        # a stream whose per-chunk timeout outlives the deadline.)
        if p.kind == "fault":
            raise RuntimeError(p.error)
        if p.kind in ("shed", "unavailable"):
            # "unavailable": a migration leg failed in a way a fresh
            # pair can serve (push failed, pool full) — retryable 503,
            # exactly like a shed, so the tier re-runs the full path.
            raise ServerUnavailable(p.error, http_status=503,
                                    retry_after=retry_after(1.0, 3.0))
        raise ValueError(p.error)

    def _await(self, p: _Pending, deadline: Optional[float]) -> _Pending:
        remaining = (None if deadline is None
                     else max(deadline - time.monotonic(), 0.0))
        if not p.event.wait(remaining):
            raise TimeoutError("request timed out")
        if p.error is not None:
            self._raise(p)
        return p

    def _cancel(self, p: _Pending) -> None:
        """Ask the scheduler to drop an unfinished request (tokens=None
        marker); its engine slot frees instead of generating unread
        tokens."""
        if not p.event.is_set():
            self._g.submit_q.put((p.rid, None, 0, None, None, None))

    @staticmethod
    def _deadline(timeout) -> Optional[float]:
        return None if timeout is None else time.monotonic() + timeout

    def generate(self, tokens, max_new: int, timeout: Optional[float] = None,
                 stop=None, return_logprobs: bool = False,
                 trace_ctx: Optional[Tuple[str, int]] = None,
                 tenant: Optional[str] = None, **samp):
        # The timeout doubles as the request's deadline: it rides the
        # submit tuple so the scheduler can shed the request if it
        # expires before prefill ever runs.
        deadline = self._deadline(timeout)
        p = self._submit(tokens, max_new, stop, samp, stream=False,
                         deadline=deadline, trace_ctx=trace_ctx,
                         tenant=tenant)
        try:
            self._await(p, deadline)
        except TimeoutError:
            # Don't strand the slot generating tokens nobody will read.
            self._cancel(p)
            raise
        if return_logprobs:
            return p.result, p.lps, p.plp, p.tlp
        return p.result

    def generate_stream(self, tokens, max_new: int,
                        timeout: Optional[float] = None, stop=None,
                        return_logprobs: bool = False,
                        trace_ctx: Optional[Tuple[str, int]] = None,
                        tenant: Optional[str] = None, **samp):
        """Yield ("delta", [token ids]) as generation progresses, then
        ("done", full output) — or ("done", (output, logprobs)) with
        return_logprobs=True. `timeout` bounds the wait per chunk (and
        doubles as the admission deadline: a stream that cannot start
        before it elapses is shed instead of prefilled)."""
        p = self._submit(tokens, max_new, stop, samp, stream=True,
                         deadline=self._deadline(timeout),
                         trace_ctx=trace_ctx, tenant=tenant)
        finished = False
        try:
            while True:
                try:
                    chunk = p.chunks.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError("request timed out mid-stream")
                if chunk is None:
                    break
                yield ("delta", chunk)
            if p.error is not None:
                self._raise(p)
            finished = True
            yield ("done",
                   (p.result, p.lps, p.plp, p.tlp) if return_logprobs
                   else p.result)
        finally:
            if not finished:
                # Consumer abandoned the stream (client disconnect tears
                # the generator down via GeneratorExit) or it errored:
                # free the slot instead of generating unread tokens.
                self._cancel(p)

    def _parse(self, payload: dict):
        if "tokens" in payload:
            tokens = np.asarray(payload["tokens"], np.int32)
        elif "text" in payload:
            if self.tokenizer is None:
                raise ValueError('"text" needs a server-side tokenizer')
            tokens = self.tokenizer.encode(payload["text"])
        else:
            raise ValueError('need "tokens" or "text"')
        max_new = int(payload.get("max_new", 32))
        stop = payload.get("stop")
        if stop is not None:
            try:
                parsed = []
                for s in stop:
                    if isinstance(s, str):
                        if self.tokenizer is None:
                            raise ValueError(
                                "string stop sequences need a server-side "
                                "tokenizer"
                            )
                        parsed.append(
                            list(map(int, self.tokenizer.encode(s)))
                        )
                    else:
                        parsed.append(list(map(int, s)))
            except (TypeError, ValueError) as e:
                # Malformed payloads must surface as HTTP 400, not a
                # dropped connection.
                raise ValueError(f"bad stop sequences: {e}")
            stop = parsed
        # Per-request sampling overrides (validated by engine.submit;
        # whitelisted so unknown payload keys can't reach **kwargs).
        try:
            samp = {
                k: float(payload[k])
                for k in ("temperature", "top_p", "min_p",
                          "presence_penalty", "frequency_penalty")
                if payload.get(k) is not None
            }
            for key in ("top_k", "min_tokens", "seed"):
                if payload.get(key) is not None:
                    v = float(payload[key])
                    if not v.is_integer():
                        raise ValueError(
                            f"{key} must be an integer, got {v}"
                        )
                    samp[key] = int(v)
            if payload.get("prompt_logprobs"):
                samp["prompt_logprobs"] = True
            if payload.get("logit_bias") is not None:
                lb = payload["logit_bias"]
                if not isinstance(lb, dict):
                    raise ValueError(
                        "logit_bias must be a {token id: bias} object"
                    )
                samp["logit_bias"] = lb  # entries validated by submit
            if payload.get("constraint") is not None:
                samp["constraint"] = self._compile_constraint(
                    payload["constraint"]
                )
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad sampling parameters: {e}")
        return tokens, max_new, stop, samp

    def _compile_constraint(self, spec):
        """Compile a constraint spec ({"regex"|"json_schema"|
        "json_object"}) to a TokenDFA over this server's tokenizer,
        cached per pattern — the compile walks the whole vocab, so a
        repeated schema must not pay it twice."""
        from shellac_tpu.inference.constraints import (
            compile_token_dfa,
            constraint_pattern,
        )

        if self.tokenizer is None:
            raise ValueError(
                "constrained decoding needs a server-side tokenizer "
                "(the grammar compiles against token strings)"
            )
        eos_id = getattr(self.engine, "eos_id", None)
        if eos_id is None:
            raise ValueError(
                "constrained decoding needs the engine's eos_id (serve "
                "--eos-id or a tokenizer that defines one)"
            )
        pattern = constraint_pattern(spec)
        cached = self._constraint_cache.get(pattern)
        if cached is None:
            self._m.constraint_cache.labels(result="miss").inc()
            t0 = time.monotonic()
            cached = compile_token_dfa(
                pattern, self.tokenizer, self.engine.cfg.vocab_size,
                eos_id,
            )
            # Compile latency is the cache-miss cost a novel schema
            # pays at admission (the walk covers the whole vocab);
            # the hit/miss counters say whether production traffic is
            # actually amortizing it.
            self._m.constraint_compile.observe(time.monotonic() - t0)
            self._constraint_cache[pattern] = cached
            # Client-supplied patterns key this cache: bound it (LRU)
            # so sustained novel schemas cannot grow host memory
            # without limit — each table is O(states x vocab) int32.
            while len(self._constraint_cache) > 32:
                self._constraint_cache.pop(
                    next(iter(self._constraint_cache))
                )
        else:
            self._m.constraint_cache.labels(result="hit").inc()
            self._constraint_cache.move_to_end(pattern)
        return cached

    def _check_logprobs(self, payload) -> bool:
        want = bool(payload.get("logprobs"))
        if want and not getattr(self.engine, "logprobs", False):
            raise ValueError(
                "logprobs requested but the server engine was not built "
                "with logprobs=True (serve --logprobs)"
            )
        return want

    def _check_top_logprobs(self, payload, want_lps: bool) -> int:
        """Per-request k of alternatives to RENDER (0 = none). The
        engine records its configured max for every request; k only
        slices."""
        k = payload.get("top_logprobs")
        if k in (None, 0, False):
            return 0
        k = int(k)
        cap = getattr(self.engine, "top_logprobs", 0)
        if k == 1 and cap == 0 and payload.get("top_logprobs_soft"):
            # OpenAI's completions `logprobs: 1` predates alternative
            # recording here; the completions translator marks it soft
            # so servers without --top-logprobs keep its long-standing
            # meaning (chosen token's logprob, no alternatives block).
            # Explicit chat/native `top_logprobs: 1` stays a loud 400
            # below — a misconfigured server must not silently degrade
            # a request that asked for alternatives by name.
            return 0
        if k < 1 or k > cap:
            raise ValueError(
                f"top_logprobs={k}: this server records "
                f"{cap or 'no'} alternatives (serve --top-logprobs N)"
            )
        if not want_lps:
            raise ValueError("top_logprobs needs logprobs=true")
        return k

    @staticmethod
    def _render_tlp(tlp, k):
        """[(ids, lps)] per token -> [[{'id', 'logprob'}] * k]."""
        return [
            [{"id": int(i), "logprob": float(v)}
             for i, v in zip(ids[:k], vals[:k])]
            for ids, vals in tlp
        ]

    # Knobs that do not compose with beam search, with their neutral
    # values: beam decode is deterministic and returns whole ranked
    # sequences, so a non-neutral sampling/streaming knob would be
    # silently ignored — loud 400 instead, the scope-honesty rule the
    # OpenAI facade already follows.
    _BEAM_NEUTRAL = {
        "stream": (None, False), "n": (None, 1), "best_of": (None, 1),
        "logprobs": (None, False), "top_logprobs": (None, 0),
        "min_tokens": (None, 0), "logit_bias": (None,),
        "presence_penalty": (None, 0, 0.0),
        "frequency_penalty": (None, 0, 0.0), "seed": (None,),
        "prompt_logprobs": (None, False), "stop": (None,),
        "temperature": (None, 0, 0.0), "top_p": (None, 1, 1.0),
        "top_k": (None,), "min_p": (None, 0, 0.0),
    }

    def _handle_beam(self, payload: dict,
                     trace_ctx: Optional[Tuple[str, int]] = None,
                     tenant: Optional[str] = None) -> dict:
        """Native beam-search request: `num_beams` (+ optional
        `length_penalty`, `constraint`) returns the ranked beams as
        {"choices": [{"tokens", "beam_score", "text"?}]}."""
        try:
            nb = int(payload["num_beams"])
            lp = float(payload.get("length_penalty", 1.0))
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad num_beams/length_penalty: {e}")
        if nb < 1:
            raise ValueError(f"num_beams must be >= 1, got {nb}")
        cap = max(4 * getattr(self.engine, "n_slots", 8), 16)
        if nb > cap:
            raise ValueError(
                f"num_beams={nb} exceeds this server's cap of {cap}"
            )
        for key, neutral in self._BEAM_NEUTRAL.items():
            if key in payload and payload[key] not in neutral:
                raise ValueError(
                    f"{key}={payload[key]!r} does not compose with "
                    "num_beams (beam search is deterministic and "
                    "unstreamed)"
                )
        tokens, max_new, _, samp = self._parse(payload)
        deadline = self._deadline(payload.get("timeout"))
        p = self._submit(
            tokens, max_new, None,
            {"_beam": {"num_beams": nb, "length_penalty": lp,
                       "constraint": samp.get("constraint")}},
            stream=False, deadline=deadline, trace_ctx=trace_ctx,
            tenant=tenant,
        )
        try:
            self._await(p, deadline)
        except TimeoutError:
            self._cancel(p)
            raise
        choices = []
        for seq, score in zip(p.result["beams"], p.result["scores"]):
            c: Dict[str, Any] = {"tokens": seq,
                                 "beam_score": round(float(score), 6)}
            if self.tokenizer is not None:
                c["text"] = self.tokenizer.decode(seq)
            choices.append(c)
        return {"choices": choices, "num_beams": nb}

    # ---- KV migration client surface (disaggregated serving) --------

    def import_kv(self, body: bytes,
                  trace_ctx: Optional[Tuple[str, int]] = None
                  ) -> Dict[str, Any]:
        """POST /kv/import: adopt a migrated request. Deserializes +
        integrity-checks the blob (400 on refusal), applies the same
        admission gates as _submit, then hands the import to the
        scheduler thread and waits for its ack. The imported request
        starts decoding IMMEDIATELY — the adopt request that follows
        attaches to it, so transfer and decode overlap with the tier's
        second leg instead of serializing behind it."""
        blob = disagg.MigrationBlob.deserialize(bytes(body))
        tid = blob.header.get("trace_id") or (
            trace_ctx[0] if trace_ctx is not None else new_trace_id()
        )
        return self._import_blob(blob, tid)

    def _import_blob(self, blob, tid: str) -> Dict[str, Any]:
        """Admit one already-deserialized migration blob under
        migration id `tid` — the shared tail of POST /kv/import and
        park-resume (which reads its blob from the durable spool
        instead of the wire)."""
        r = blob.header.get("request") or {}
        with self._lock:
            if self._fatal is not None:
                raise RuntimeError(self._fatal)
            if self._closed.is_set():
                raise RuntimeError("server closed")
            g = self._g
            if self._recovering or g.dead:
                self._m.rejects.labels(reason="recovering",
                                       tenant="").inc()
                raise ServerUnavailable(
                    "server recovering from an engine fault; retry",
                    http_status=503, retry_after=retry_after(3.0, 8.0),
                )
            if self._draining:
                self._m.rejects.labels(reason="draining",
                                       tenant="").inc()
                raise ServerUnavailable(
                    "server draining: not admitting migrations; retry "
                    "elsewhere",
                    http_status=503, retry_after=retry_after(1.0, 4.0),
                )
            if (self.max_pending is not None
                    and len(self._pending) >= self.max_pending):
                self._m.rejects.labels(reason="overloaded",
                                       tenant="").inc()
                raise ServerUnavailable(
                    f"server overloaded: {len(self._pending)} requests "
                    f"pending (max_pending={self.max_pending})",
                    http_status=429, retry_after=retry_after(1.0, 3.0),
                )
            stale = self._adoptions.pop(tid, None)
            if stale is not None and not stale[0].event.is_set():
                # A re-run of the same migration (the tier retried
                # after a lost ack): the prior import is now orphaned
                # — cancel it instead of letting it decode to
                # completion unadopted, pinning pool blocks.
                g.submit_q.put((stale[0].rid, None, 0, None, None,
                                None))
            rid = next(self._ids)
            trace = self._m.trace(trace_id=tid, recorder=self._recorder)
            stop = r.get("stop")
            holdback = (max((len(s) for s in stop), default=0)
                        if stop else 0)
            p = _Pending(rid, stream=True, holdback=holdback,
                         trace=trace)
            trace.record("admit", rid=rid, src="server",
                         kind="kv-import",
                         prompt_len=len(r.get("tokens") or ()),
                         pending=len(self._pending) + 1)
            if blob.header.get("complete"):
                # The request finished at its prefill (max_new=1,
                # instant EOS, stop match): settle now — no engine, no
                # pool, nothing to decode.
                trace.prefill_start()
                trace.first_token()
                p.result = list(r.get("out") or ())
                p.lps = r.get("lps") or None
                p.plp = r.get("plp")
                if r.get("tlp") is not None:
                    p.tlp = [(list(ids), list(vals))
                             for ids, vals in r["tlp"]]
                trace.finish(len(p.result))
                p.finish()
                self._adoptions[tid] = (p, time.monotonic())
                self._m.migrations.labels(outcome="import").inc()
                return {"imported": True, "migration_id": tid,
                        "complete": True, "trace_id": tid}
            self._pending[rid] = p
            self._adoptions[tid] = (p, time.monotonic())
            ack = _ImportAck()
            g.submit_q.put((
                rid, np.asarray(r.get("tokens") or [], np.int32),
                int(r.get("max_new") or 1), stop,
                {"_kv_import": (blob, ack, tid)}, None,
            ))
        if not ack.event.wait(timeout=60.0):
            raise ServerUnavailable(
                "kv import not processed in time",
                http_status=503, retry_after=retry_after(1.0, 3.0),
            )
        if ack.error is not None:
            if ack.retryable:
                raise ServerUnavailable(
                    ack.error, http_status=503,
                    retry_after=retry_after(1.0, 3.0),
                )
            raise ValueError(ack.error)
        return {"imported": True, "migration_id": tid,
                "slot": ack.slot, "complete": False, "trace_id": tid}

    # ---- KV fabric surface (directory feed / seed / push / park) ----

    def prefix_manifest(self, since: int = -1) -> Dict[str, Any]:
        """GET /kv/prefixes: the backend's prefix-cache manifest for
        the tier's directory. Read handler-side while the scheduler
        mutates the registry, so a torn iteration (RuntimeError from a
        resized dict) just retries; after a few collisions it reports
        "unchanged" — the directory is a routing hint fed on every
        sweep, not a ledger, so the next poll catches up."""
        for _ in range(3):
            try:
                return self.engine.cache_backend.prefix_manifest(since)
            except RuntimeError:
                continue
        return {"supported": True, "version": since, "unchanged": True}

    def seed_kv(self, body: bytes,
                trace_ctx: Optional[Tuple[str, int]] = None
                ) -> Dict[str, Any]:
        """POST /kv/seed: adopt a prefix-seed blob into the prefix
        registry. Integrity failures (crc, truncation) refuse at
        deserialize with the registry untouched; the seed itself runs
        on the scheduler thread and never evicts live state."""
        try:
            blob = disagg.MigrationBlob.deserialize(bytes(body))
        except ValueError:
            self._m.fabric_seed_rejects.labels(reason="corrupt").inc()
            raise
        tid = blob.header.get("trace_id") or (
            trace_ctx[0] if trace_ctx is not None else new_trace_id()
        )
        with self._lock:
            if self._fatal is not None:
                raise RuntimeError(self._fatal)
            if self._closed.is_set():
                raise RuntimeError("server closed")
            g = self._g
            if self._recovering or g.dead:
                raise ServerUnavailable(
                    "server recovering from an engine fault; retry",
                    http_status=503, retry_after=retry_after(3.0, 8.0),
                )
            if self._draining:
                raise ServerUnavailable(
                    "server draining: not adopting seeds",
                    http_status=503, retry_after=retry_after(1.0, 4.0),
                )
            ack = _ImportAck()
            g.submit_q.put((
                next(self._ids), np.zeros(0, np.int32), 0, None,
                {"_kv_seed": (blob, ack, tid)}, None,
            ))
        if not ack.event.wait(timeout=60.0):
            raise ServerUnavailable(
                "kv seed not processed in time",
                http_status=503, retry_after=retry_after(1.0, 3.0),
            )
        if ack.error is not None:
            if ack.retryable:
                raise ServerUnavailable(
                    ack.error, http_status=503,
                    retry_after=retry_after(1.0, 3.0),
                )
            raise ValueError(ack.error)
        return {"seeded": ack.slot, "trace_id": tid}

    def push_chain(self, payload: dict,
                   trace_ctx: Optional[Tuple[str, int]] = None
                   ) -> Dict[str, Any]:
        """POST /kv/push {"chain": <tip hex>, "target": <url>}: export
        the cached chain ending at `chain` and ship it to `target`'s
        /kv/seed — the leg the tier's replication planner drives
        against a holder replica. The scheduler thread only pays the
        device pull; this handler thread owns serialize + HTTP."""
        tid = (trace_ctx[0] if trace_ctx is not None
               else new_trace_id())
        tip_hex = payload.get("chain")
        target = payload.get("target")
        if not isinstance(tip_hex, str) or not tip_hex:
            raise ValueError(
                'kv push needs "chain": the chain tip hash (hex)'
            )
        if not isinstance(target, str) or "://" not in target:
            raise ValueError(
                'kv push needs "target": the receiving replica base URL'
            )
        try:
            tip = bytes.fromhex(tip_hex)
        except ValueError:
            raise ValueError(f"bad chain hash {tip_hex!r}")
        with self._lock:
            if self._fatal is not None:
                raise RuntimeError(self._fatal)
            if self._closed.is_set():
                raise RuntimeError("server closed")
            g = self._g
            if self._recovering or g.dead:
                raise ServerUnavailable(
                    "server recovering from an engine fault; retry",
                    http_status=503, retry_after=retry_after(3.0, 8.0),
                )
            ack = _ImportAck()
            g.submit_q.put((
                next(self._ids), np.zeros(0, np.int32), 0, None,
                {"_kv_export_chain": (tip, ack, tid)}, None,
            ))
        if not ack.event.wait(timeout=60.0):
            raise ServerUnavailable(
                "chain export not processed in time",
                http_status=503, retry_after=retry_after(1.0, 3.0),
            )
        if ack.error is not None:
            if ack.retryable:
                raise ServerUnavailable(
                    ack.error, http_status=503,
                    retry_after=retry_after(1.0, 3.0),
                )
            raise ValueError(ack.error)
        blob = ack.slot  # the export ack carries the blob
        data = blob.serialize()
        headers = {"Content-Type": "application/octet-stream",
                   TRACE_HEADER: format_trace_header(tid, 0)}
        t0 = time.monotonic()
        try:
            req = urllib.request.Request(
                target.rstrip("/") + "/kv/seed", data=data,
                headers=headers,
            )
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                body = json.loads(resp.read() or b"{}")
        except Exception as e:  # noqa: BLE001 — one retryable leg
            raise ServerUnavailable(
                f"could not deliver seed to {target}: "
                f"{type(e).__name__}: {e}",
                http_status=503, retry_after=retry_after(1.0, 3.0),
            )
        dt = time.monotonic() - t0
        self._m.kv_transfer_seconds.observe(dt, exemplar=tid)
        self._m.kv_transfer_bytes.observe(float(len(data)),
                                          exemplar=tid)
        if self._recorder is not None:
            self._recorder.record(
                tid, "kv-push", chain=tip_hex[:12], target=target,
                bytes=len(data), seeded=body.get("seeded"),
                transfer_s=round(dt, 6),
            )
        return {"pushed": True, "bytes": len(data),
                "seeded": body.get("seeded"),
                "transfer_s": round(dt, 6), "trace_id": tid}

    def _handle_resume(self, payload: dict,
                       trace_ctx: Optional[Tuple[str, int]] = None
                       ) -> dict:
        """Native resume request ({"resume": <park id>}): read the
        parked blob back from the durable spool (crc-verified), import
        it like a migration, and attach exactly like an adopt — so a
        parked session continues on ANY replica that mounts the park
        directory, byte-identical to never having been parked."""
        if self._park is None:
            raise ValueError(
                '"resume" needs serve --park-dir on this replica'
            )
        park_id = str(payload.get("resume"))
        try:
            blob = self._park.get(park_id)
        except KeyError:
            self._m.fabric_resumed.labels(outcome="missing").inc()
            raise ValueError(
                f"unknown park id {park_id!r} (never parked, trimmed "
                "by the size cap, or quarantined)"
            )
        except ValueError as e:
            # Torn/corrupt spool file: quarantined by the store so the
            # next retry does not re-read the same bad sectors. Loud —
            # a server fault, not a bad request.
            self._m.fabric_resumed.labels(outcome="torn").inc()
            raise RuntimeError(
                f"parked blob {park_id!r} failed integrity read-back "
                f"and was quarantined: {e}"
            )
        self._import_blob(blob, park_id)
        self._m.fabric_resumed.labels(outcome="ok").inc()
        if self._recorder is not None:
            self._recorder.record(
                trace_ctx[0] if trace_ctx is not None else None,
                "fabric-resume", park_id=park_id,
                complete=bool(blob.header.get("complete")),
            )
        sub = {k: v for k, v in payload.items() if k != "resume"}
        sub["adopt"] = park_id
        return self._handle_adopt(sub, trace_ctx=trace_ctx)

    def _handle_migrate(self, payload: dict,
                        trace_ctx: Optional[Tuple[str, int]] = None
                        ) -> dict:
        """Native prefill-only request ({"prefill_only": true,
        "migrate_to": <decode URL>}): prefill, freeze, export, push —
        answers with the migration ack once the decode replica holds
        the KV. The tier's disaggregated path drives this as leg 1."""
        target = payload.get("migrate_to")
        if payload.get("park"):
            # Park leg: same prefill/freeze/export path, but the blob
            # lands in the durable spool instead of a decode replica.
            if target is not None:
                raise ValueError(
                    "park and migrate_to are mutually exclusive (a "
                    "parked blob has no decode target yet)"
                )
            if self._park is None:
                raise ValueError(
                    '"park" needs serve --park-dir on this replica'
                )
            target = "park:" + new_trace_id()
        elif not isinstance(target, str) or "://" not in target:
            raise ValueError(
                'prefill_only needs "migrate_to": the decode replica '
                'base URL (or "park": true with serve --park-dir)'
            )
        for key in ("stream", "num_beams", "adopt"):
            if payload.get(key):
                raise ValueError(
                    f"{key} does not compose with prefill_only"
                )
        try:
            n = int(payload.get("n", 1) or 1)
            best_of = int(payload.get("best_of", n) or n)
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad n/best_of: {e}")
        if n != 1 or best_of != 1:
            raise ValueError(
                "n/best_of > 1 do not compose with prefill_only "
                "(fan-out is tier-side)"
            )
        if payload.get("constraint") is not None:
            raise ValueError(
                "constraint does not compose with prefill_only (the "
                "compiled DFA table does not migrate)"
            )
        tokens, max_new, stop, samp = self._parse(payload)
        deadline = self._deadline(payload.get("timeout"))
        p = self._submit(tokens, max_new, stop,
                         {**samp, "_migrate": target}, stream=False,
                         deadline=deadline, trace_ctx=trace_ctx)
        try:
            self._await(p, deadline)
        except TimeoutError:
            self._cancel(p)
            raise
        return dict(p.result)

    def _pop_adoption(self, payload: dict) -> _Pending:
        mid = str(payload.get("adopt"))
        with self._lock:
            ent = self._adoptions.pop(mid, None)
        if ent is None:
            # Retryable by contract: the tier re-runs the full
            # prefill->migrate path on a fresh pair (a 4xx here would
            # read as permanent and fail the client).
            raise ServerUnavailable(
                f"unknown migration id {mid!r} (never imported, "
                "expired, or already adopted); re-run the migration",
                http_status=503, retry_after=retry_after(1.0, 3.0),
            )
        return ent[0]

    def _handle_adopt(self, payload: dict,
                      trace_ctx: Optional[Tuple[str, int]] = None
                      ) -> dict:
        """Native adopt request ({"adopt": <migration id>}): attach to
        an imported request and answer exactly like a local /generate
        would — the disaggregated path's leg 2, byte-identical to
        monolithic serving."""
        want_lps = self._check_logprobs(payload)
        tlk = self._check_top_logprobs(payload, want_lps)
        p = self._pop_adoption(payload)
        deadline = self._deadline(payload.get("timeout"))
        try:
            self._await(p, deadline)
        except TimeoutError:
            self._cancel(p)
            raise
        result = self._format_completion(p.result, p.lps, want_lps,
                                         plp=p.plp, tlp=p.tlp, tlk=tlk)
        result["trace_id"] = (trace_ctx[0] if trace_ctx is not None
                              else p.trace.trace_id)
        return result

    def _adopt_stream(self, payload: dict,
                      trace_ctx: Tuple[str, int]):
        """Streaming adopt: the imported request's chunk queue drains
        as ndjson deltas, then the same final record a local stream
        would end with."""
        want_lps = self._check_logprobs(payload)
        tlk = self._check_top_logprobs(payload, want_lps)
        p = self._pop_adoption(payload)
        timeout = payload.get("timeout")
        tid = trace_ctx[0]
        finished = False
        try:
            while True:
                try:
                    chunk = p.chunks.get(timeout=timeout)
                except queue.Empty:
                    raise TimeoutError("request timed out mid-stream")
                if chunk is None:
                    break
                yield {"tokens": chunk, "trace_id": tid}
            if p.error is not None:
                self._raise(p)
            finished = True
            out = p.result
            final: Dict[str, Any] = {"done": True, "tokens": out,
                                     "trace_id": tid}
            if want_lps:
                final["logprobs"] = p.lps
            if tlk and p.tlp is not None:
                final["top_logprobs"] = self._render_tlp(p.tlp, tlk)
            if p.plp is not None:
                final["prompt_logprobs"] = _render_plp(p.plp)
            if self.tokenizer is not None:
                final["text"] = self.tokenizer.decode(out)
            yield final
        finally:
            if not finished:
                self._cancel(p)

    def _tool_context(self, payload: dict):
        """Validate `tools`/`tool_choice` on a native payload and
        return the ToolContext (None when the request declares no
        tools). The caller compiles ctx.pattern through the DFA cache
        and parses the finished output back into tool_calls. The
        native surface takes the OpenAI tool shapes verbatim — the
        chat facade forwards them here — but does NOT render tools
        into the prompt: a native caller owns its prompt."""
        from shellac_tpu.inference.tools import parse_payload_tools

        ctx = parse_payload_tools(payload)
        if ctx is None:
            return None
        if self.tokenizer is None:
            raise ValueError(
                "tools need a server-side tokenizer (the tool grammar "
                "compiles against token strings)"
            )
        if payload.get("constraint") is not None:
            raise ValueError(
                "tools do not compose with an explicit constraint "
                "(the tool grammar IS the request's constraint)"
            )
        return ctx

    def _tool_constraint(self, samp: dict, tool_ctx) -> None:
        if tool_ctx is not None and tool_ctx.pattern is not None:
            samp["constraint"] = self._compile_constraint(
                {"regex": tool_ctx.pattern}
            )

    def _tool_outcome(self, text: str, calls) -> None:
        # The grammar's free-text branch can never START with '<'
        # (entering the sentinel commits to the tool branch), so any
        # unparsed '<'-prefixed output — including a budget cut inside
        # the sentinel itself — is a truncated call, not free text.
        outcome = ("call" if calls is not None
                   else "truncated" if text.startswith("<")
                   else "text")
        self._m.tool_requests.labels(outcome=outcome).inc()

    def handle(self, payload: dict,
               trace_ctx: Optional[Tuple[str, int]] = None,
               tenant: Optional[str] = None) -> dict:
        # One trace id for the whole request, fan-out included: resolve
        # it here so every sub-submit (and the response echo) agrees.
        if trace_ctx is None:
            trace_ctx = (new_trace_id(), 0)
        tool_ctx = self._tool_context(payload)
        if payload.get("resume") is not None:
            if tool_ctx is not None:
                raise ValueError("tools do not compose with resume")
            return self._handle_resume(payload, trace_ctx=trace_ctx)
        if payload.get("prefill_only"):
            if tool_ctx is not None:
                raise ValueError(
                    "tools do not compose with prefill_only (tool "
                    "grammar state does not migrate)"
                )
            result = self._handle_migrate(payload, trace_ctx=trace_ctx)
            result["trace_id"] = trace_ctx[0]
            return result
        if payload.get("adopt") is not None:
            if tool_ctx is not None:
                raise ValueError("tools do not compose with adopt")
            return self._handle_adopt(payload, trace_ctx=trace_ctx)
        if payload.get("num_beams") is not None:
            if tool_ctx is not None:
                raise ValueError(
                    "tools do not compose with num_beams (a beam is a "
                    "ranked whole sequence, not an assistant turn)"
                )
            result = self._handle_beam(payload, trace_ctx=trace_ctx,
                                       tenant=tenant)
            result["trace_id"] = trace_ctx[0]
            return result
        tokens, max_new, stop, samp = self._parse(payload)
        self._tool_constraint(samp, tool_ctx)
        want_lps = self._check_logprobs(payload)
        tlk = self._check_top_logprobs(payload, want_lps)
        n, best_of = self._parse_n(payload, samp)
        if n == 1 and best_of == 1:
            out, lps, plp, tlp = self.generate(
                tokens, max_new, timeout=payload.get("timeout"), stop=stop,
                return_logprobs=True, trace_ctx=trace_ctx, tenant=tenant,
                **samp,
            )
            result = self._format_completion(
                out, lps, want_lps, plp=plp, tlp=tlp, tlk=tlk,
                tool_ctx=tool_ctx,
            )
            result["trace_id"] = trace_ctx[0]
            return result
        # Parallel sampling: best_of independent completions share the
        # slot batch (and, on a paged+prefix engine, their prompt KV);
        # the n best by mean token logprob come back as "choices". The
        # prompt is identical across the fan-out, so prompt logprobs
        # (echo) are computed ONCE, on the first sub-request only.
        rest_samp = {k: v for k, v in samp.items()
                     if k != "prompt_logprobs"}
        # One overall deadline for the whole fan-out — not a fresh
        # clock per completion — shared with the scheduler so unstarted
        # siblings shed once it passes.
        deadline = self._deadline(payload.get("timeout"))
        pendings = []
        try:
            for i in range(best_of):
                pendings.append(self._submit(
                    tokens, max_new, stop,
                    samp if i == 0 else rest_samp, stream=False,
                    deadline=deadline, trace_ctx=trace_ctx,
                    tenant=tenant,
                ))
        except RuntimeError:
            # Admission cap (or a fault) hit mid-fan-out: release the
            # siblings already submitted before surfacing the refusal.
            for p in pendings:
                self._cancel(p)
            raise
        choices = []
        plp = None
        try:
            for p in pendings:
                self._await(p, deadline)
                choices.append((p.result, p.lps, p.tlp))
                if p.plp is not None:
                    plp = p.plp
        except (TimeoutError, ValueError, RuntimeError):
            # Don't strand the rest: unfinished siblings would keep
            # occupying slots generating tokens nobody will read.
            for p in pendings:
                self._cancel(p)
            raise
        if best_of > n:
            # Rank by mean logprob (length-normalized); engine logprobs
            # are guaranteed on because _parse_n requires the flag. A
            # completion emptied by a stop match ranks last, not first
            # (an empty mean would otherwise score a perfect 0.0).
            def score(c):
                return (sum(c[1]) / len(c[1])) if c[1] else float("-inf")

            choices.sort(key=score, reverse=True)
        result: Dict[str, Any] = {"choices": [
            self._format_completion(out, lps, want_lps, tlp=tlp, tlk=tlk,
                                    tool_ctx=tool_ctx)
            for out, lps, tlp in choices[:n]
        ]}
        if plp is not None:
            result["prompt_logprobs"] = _render_plp(plp)
        result["trace_id"] = trace_ctx[0]
        return result

    def _format_completion(self, out, lps, want_lps,
                           plp=None, tlp=None, tlk=0,
                           tool_ctx=None) -> Dict[str, Any]:
        result: Dict[str, Any] = {"tokens": out}
        if want_lps:
            result["logprobs"] = lps
        if tlk and tlp is not None:
            result["top_logprobs"] = self._render_tlp(tlp, tlk)
        if plp is not None:
            result["prompt_logprobs"] = _render_plp(plp)
        if self.tokenizer is not None:
            result["text"] = self.tokenizer.decode(out)
            if tool_ctx is not None and tool_ctx.pattern is not None:
                from shellac_tpu.inference.tools import parse_tool_calls

                content, calls = parse_tool_calls(
                    result["text"], tool_ctx.mode
                )
                self._tool_outcome(result["text"], calls)
                if calls is not None:
                    result["tool_calls"] = calls
                else:
                    # Free text (auto) or a length-truncated call:
                    # honest content, never a fabricated call.
                    result["content"] = content
        return result

    def _parse_n(self, payload: dict, samp: dict):
        """Validate n (completions returned) and best_of (sampled)."""
        try:
            n = int(payload.get("n", 1))
            best_of = int(payload.get("best_of", n))
        except (TypeError, ValueError) as e:
            raise ValueError(f"bad n/best_of: {e}")
        if n < 1 or best_of < n:
            raise ValueError(f"need best_of >= n >= 1, got n={n}, "
                             f"best_of={best_of}")
        cap = max(4 * getattr(self.engine, "n_slots", 8), 16)
        if best_of > cap:
            raise ValueError(
                f"best_of={best_of} exceeds this server's cap of {cap} "
                "(4x slot count): one request would monopolize the "
                "engine for every other client"
            )
        if best_of == 1:
            return n, best_of
        temp = samp.get("temperature",
                        getattr(self.engine, "_defaults", {}).get(
                            "temperature", 0.0))
        if temp == 0.0:
            raise ValueError(
                "n/best_of > 1 with greedy sampling would return "
                "identical completions; set a temperature"
            )
        if best_of > n and not getattr(self.engine, "logprobs", False):
            raise ValueError(
                "best_of > n ranks completions by logprob; start the "
                "server with logprobs enabled (serve --logprobs)"
            )
        return n, best_of

    def handle_stream(self, payload: dict,
                      trace_ctx: Optional[Tuple[str, int]] = None,
                      tenant: Optional[str] = None):
        """Yield response dicts for a streaming request: delta lines
        {"tokens": [...]}, then {"done": true, "tokens", "text"?,
        "logprobs"?}. Every record carries the request's `trace_id`,
        so a stream that fails after its 200 is attributable from the
        client's capture alone. Logprobs (when requested) arrive on
        the final record only. Parse errors raise before the first
        yield (clean HTTP 400)."""
        if trace_ctx is None:
            trace_ctx = (new_trace_id(), 0)
        if payload.get("num_beams") is not None:
            raise ValueError(
                "num_beams does not compose with streaming (beams are "
                "ranked whole sequences; request them unstreamed)"
            )
        if payload.get("prefill_only"):
            raise ValueError(
                "prefill_only does not compose with streaming (the "
                "migration ack is a single JSON object)"
            )
        if payload.get("adopt") is not None:
            if payload.get("tools"):
                raise ValueError("tools do not compose with adopt")
            yield from self._adopt_stream(payload, trace_ctx)
            return
        tool_ctx = self._tool_context(payload)
        tokens, max_new, stop, samp = self._parse(payload)
        self._tool_constraint(samp, tool_ctx)
        want_lps = self._check_logprobs(payload)
        tlk = self._check_top_logprobs(payload, want_lps)
        n, best_of = self._parse_n(payload, samp)
        if n != 1 or best_of != 1:
            raise ValueError("streaming does not support n/best_of > 1")
        # Tool-enabled streams carry, besides the raw token deltas, a
        # `tool_stream` field with incremental OpenAI-shaped
        # tool_calls deltas / decided free-text content — produced by
        # ONE scanner so SSE, ndjson, and the non-streamed parse
        # cannot disagree. Stop-sequence holdback already guarantees
        # the deltas never overrun the final (trimmed) output.
        scanner = None
        streamed: list = []
        if tool_ctx is not None and tool_ctx.pattern is not None:
            from shellac_tpu.inference.tools import (
                ToolCallStreamParser,
                events_to_stream,
                safe_stream_text,
            )

            scanner = ToolCallStreamParser(tool_ctx.mode)
        stream = self.generate_stream(
            tokens, max_new, timeout=payload.get("timeout"), stop=stop,
            return_logprobs=True, trace_ctx=trace_ctx, tenant=tenant,
            **samp,
        )
        tid = trace_ctx[0]
        for kind, val in stream:
            if kind == "delta":
                rec: Dict[str, Any] = {"tokens": val, "trace_id": tid}
                if scanner is not None:
                    streamed.extend(val)
                    ts = events_to_stream(scanner.feed(safe_stream_text(
                        self.tokenizer.decode(streamed)
                    )))
                    if ts is not None:
                        rec["tool_stream"] = ts
                yield rec
            else:
                out, lps, plp, tlp = val
                final: Dict[str, Any] = {"done": True, "tokens": out,
                                         "trace_id": tid}
                if want_lps:
                    final["logprobs"] = lps
                if tlk and tlp is not None:
                    final["top_logprobs"] = self._render_tlp(tlp, tlk)
                if plp is not None:
                    final["prompt_logprobs"] = _render_plp(plp)
                if self.tokenizer is not None:
                    final["text"] = self.tokenizer.decode(out)
                    if scanner is not None:
                        # The authoritative text (stop-trimmed) settles
                        # the scan: tail events ride the final record,
                        # plus the COMPLETE parsed call list.
                        ts = events_to_stream(scanner.feed(final["text"]))
                        if ts is not None:
                            final["tool_stream"] = ts
                        calls = scanner.result()
                        self._tool_outcome(final["text"], calls)
                        if calls is not None:
                            final["tool_calls"] = calls
                yield final

    def _prompt_lp_capable(self) -> bool:
        eng = self.engine
        if not hasattr(eng, "finished_prompt_logprobs"):
            return False
        # Paged AND speculative engines score prompts now (the spec
        # engine's target prefill runs the same scoring forwards); the
        # one remaining hole is the prefix cache — a cache hit skips
        # exactly the scoring forward passes.
        return (getattr(eng, "_scores_prompts", True)
                and not getattr(eng, "prefix_cache", False))

    # ---- OpenAI-compatible façade -----------------------------------

    def handle_openai(self, payload: dict, chat: bool,
                      trace_ctx: Optional[Tuple[str, int]] = None,
                      tenant: Optional[str] = None) -> dict:
        from shellac_tpu.inference.openai_api import (
            chat_to_native,
            completion_response,
            completion_to_native,
        )

        # trace_ctx passes straight through to handle(), which mints
        # on None — no need to duplicate the fallback here.
        # OpenAI requests may carry the tenant as the `user` field;
        # an explicit x-shellac-tenant header wins.
        if tenant is None and payload.get("user"):
            tenant = str(payload["user"])
        native = (chat_to_native(payload, self.tokenizer) if chat
                  else completion_to_native(payload, self.tokenizer))
        echo = bool(native.pop("echo", False))
        if native.get("prompt_logprobs") and not self._prompt_lp_capable():
            raise ValueError(
                "echo with logprobs is unavailable on this server: the "
                "engine cannot score prompts (a prefix-cached prefill "
                "skips the scoring forwards)"
            )
        tokens = self._parse(native)[0]
        # Hand handle() the ids so the prompt is not tokenized twice.
        native.pop("text", None)
        native["tokens"] = [int(t) for t in tokens]
        prompt_tokens = len(tokens)
        max_new = int(native.get("max_new", 32))
        result = self.handle(native, trace_ctx=trace_ctx,
                             tenant=tenant)
        return completion_response(
            result, model=self.model_name, prompt_tokens=prompt_tokens,
            max_new=max_new, tokenizer=self.tokenizer, chat=chat,
            echo=echo, prompt_ids=[int(t) for t in tokens],
        )

    def handle_openai_stream(self, payload: dict, chat: bool,
                             trace_ctx: Optional[Tuple[str, int]] = None,
                             tenant: Optional[str] = None):
        """Yield OpenAI SSE chunk objects (the HTTP layer frames them
        as `data:` lines and appends [DONE]). Each chunk carries the
        request's `trace_id` alongside the OpenAI fields — unknown
        keys are ignored by SDKs, and a severed stream stays
        attributable from the client's capture."""
        from shellac_tpu.inference.openai_api import (
            StreamTranslator,
            chat_to_native,
            completion_to_native,
        )

        if trace_ctx is None:
            trace_ctx = (new_trace_id(), 0)
        if tenant is None and payload.get("user"):
            tenant = str(payload["user"])
        native = (chat_to_native(payload, self.tokenizer) if chat
                  else completion_to_native(payload, self.tokenizer))
        if native.pop("echo", False):
            raise ValueError(
                "echo does not compose with streaming (the prompt is "
                "known to the client; request it unstreamed)"
            )
        native.pop("prompt_logprobs", None)
        max_new = int(native.get("max_new", 32))
        translator = StreamTranslator(
            model=self.model_name, tokenizer=self.tokenizer, chat=chat,
            # Tool-enabled chat streams translate the server's
            # tool_stream scan, not the raw token text (the one
            # scanner keeps SSE and ndjson surfaces in agreement).
            tool_mode=bool(native.get("tools"))
            and native.get("tool_choice") != "none",
        )
        for record in self.handle_stream(native, trace_ctx=trace_ctx,
                                         tenant=tenant):
            for chunk in translator.feed(record, max_new):
                chunk["trace_id"] = trace_ctx[0]
                yield chunk

    def close(self):
        if self._spool is not None:
            # After the spool closes, late recorder events fall back
            # to append-and-reopen inside EventSpool; closing here
            # just releases the handle on the orderly path.
            self._spool.close()
        if self._push_pool is not None:
            # In-flight pushes settle their pendings or are failed by
            # the sweep below; new pushes cannot start (closed).
            self._push_pool.shutdown(wait=False)
        with self._lock:
            self._closed.set()
            g = self._g
            g.stop.set()
        g.thread.join(timeout=2)
        with self._lock:
            # Whatever is still pending will never finish (the
            # scheduler delivered its last results before exiting, or
            # is wedged): fail the requests loudly NOW instead of
            # leaving blocked generate() callers waiting out their
            # full timeout. Racing a final in-flight delivery is
            # benign — whoever pops the pending first settles it.
            self._fail_pending_locked(
                "server closed before the request completed"
            )
        if getattr(g.engine, "is_primary", False):
            # Multi-host: the followers must be released with a STOP
            # broadcast, and only after the scheduler thread (the
            # broadcast's other participant on this process) has truly
            # exited — two threads must not broadcast at once, and a
            # slow step can easily outlive the 2s fast path above. Only
            # a thread wedged WELL beyond a step (dead transport) may
            # leave shutdown unsent; at that point the followers'
            # collectives are failing on their own.
            deadline = time.monotonic() + 300
            while g.thread.is_alive() and time.monotonic() < deadline:
                g.thread.join(timeout=5)
            if not g.thread.is_alive():
                g.engine.shutdown()


def make_http_server(server: InferenceServer, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    from shellac_tpu.inference.openai_api import stream_error_payload

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, obj: dict, headers: dict = None):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _send_unavailable(self, e: "ServerUnavailable",
                              openai: bool = False,
                              trace_id: Optional[str] = None):
            err = ({"error": {"message": str(e),
                              "type": "overloaded_error"}}
                   if openai else {"error": str(e)})
            headers = {
                "Retry-After": str(max(1, int(round(e.retry_after)))),
            }
            if trace_id is not None:
                headers[REQUEST_ID_HEADER] = trace_id
            self._send(e.http_status, err, headers=headers)

        def do_GET(self):
            # Error responses carry the trace id too (adopted from the
            # header or minted): a rejected request is exactly the one
            # its sender wants to look up in the recorder.
            rid_hdr = {REQUEST_ID_HEADER:
                       adopt_trace(self.headers.get(TRACE_HEADER))[0]}
            if self.path == "/v1/models":
                self._send(200, {
                    "object": "list",
                    "data": [{
                        "id": server.model_name, "object": "model",
                        "owned_by": "shellac_tpu",
                    }],
                })
            elif self.path == "/health":
                # A real readiness signal: 200 only while serving.
                # Recovering and failed both 503 so load balancers pull
                # the backend; the body says which (and why, when
                # fatal).
                h = server.health()
                self._send(200 if h["ok"] else 503, h,
                           headers=rid_hdr)
            elif self.path == "/stats":
                eng = server.engine
                self._send(200, {
                    **eng.stats,
                    "pending": eng.pending,
                    "slots_busy": sum(r is not None for r in eng._slots),
                    "n_slots": eng.n_slots,
                    "decode_ticks": eng.decode_ticks,
                    # How the window length was chosen ("fixed" |
                    # "auto" pending | "auto-tuned") and whether the
                    # decode loop runs the two-deep overlapped
                    # dispatch pipeline — the tier's load scoring
                    # reads these alongside the host-overhead
                    # histogram at /metrics.
                    "decode_ticks_source": getattr(
                        eng, "decode_ticks_source", "fixed"),
                    "overlap_decode": bool(
                        getattr(eng, "overlap_decode", False)),
                    # The admission-side twins: is prefill dispatch
                    # overlapped, what chunk size is live, and how it
                    # was chosen ("fixed" | "auto" pending |
                    # "auto-tuned") — the stats dict already mirrors
                    # overlap_prefill/prefill_chunk numerically at
                    # /metrics (shellac_engine_*).
                    "overlap_prefill": bool(
                        getattr(eng, "overlap_prefill", False)),
                    "prefill_chunk_source": getattr(
                        eng, "prefill_chunk_source", "fixed"),
                    # Supervisor state: /stats stays 200 through an
                    # outage (scrapers keep collecting); readiness
                    # lives at /health.
                    "role": server.role,
                    "status": server.status,
                    "fatal": server._fatal,
                    "restarts": server.restarts,
                    "generation": server._g.gen,
                    "shed": server.shed,
                    "uptime_s": round(server.uptime_s, 3),
                    # Multi-tenant QoS: per-tenant admission counters,
                    # per-class queue depths, parked preemption state
                    # (empty object when untenanted).
                    "qos": server.qos_snapshot(),
                    # p50/p90/p99 latency digests from the obs
                    # histograms (null until requests have completed).
                    **server.latency_summary(),
                })
            elif self.path == "/metrics":
                if not server.metrics_enabled:
                    self._send(404, {
                        "error": "metrics disabled (serve --no-metrics)",
                    }, headers=rid_hdr)
                    return
                # Prometheus text exposition. Like /stats, this stays
                # 200 through an outage so scrapers keep collecting.
                body = server.metrics_text().encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type",
                    "text/plain; version=0.0.4; charset=utf-8",
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
            elif self.path.startswith("/kv/prefixes"):
                # KV-fabric directory feed: what this replica's prefix
                # cache holds (delta-polled — ?since=<version> answers
                # "unchanged" when nothing moved).
                qs = urllib.parse.urlsplit(self.path).query
                try:
                    since = int(urllib.parse.parse_qs(qs).get(
                        "since", ["-1"])[0])
                except ValueError:
                    self._send(400, {"error": "bad since value"},
                               headers=rid_hdr)
                    return
                self._send(200, server.prefix_manifest(since),
                           headers=rid_hdr)
            elif self.path.startswith("/debug/"):
                # Introspection surface: the in-flight table and per-
                # trace timelines. 404 wholesale under --no-debug (the
                # --no-metrics pattern: absent, not forbidden).
                if not server.debug_enabled:
                    self._send(404, {
                        "error": "debug endpoints disabled "
                                 "(serve --no-debug)",
                    }, headers=rid_hdr)
                elif self.path == "/debug/requests":
                    self._send(200, server.debug_requests())
                elif self.path == "/debug/incidents":
                    if server.incidents is None:
                        self._send(400, {
                            "error": "incident bundles need serve "
                                     "--incident-dir",
                        }, headers=rid_hdr)
                    else:
                        self._send(200, {
                            "incidents": server.incidents.list(),
                            "dir": server.incidents.incident_dir,
                            "last": server.incidents.last,
                        })
                elif self.path.startswith("/debug/incident/"):
                    bid = self.path[len("/debug/incident/"):]
                    out = (server.incidents.load(bid)
                           if server.incidents is not None else None)
                    if out is None:
                        self._send(404, {
                            "error": f"no incident bundle {bid!r} "
                                     "(unknown id, evicted by "
                                     "retention, or no --incident-dir)",
                        }, headers=rid_hdr)
                    else:
                        self._send(200, out)
                elif self.path.startswith("/debug/request/"):
                    tid = self.path[len("/debug/request/"):]
                    out = server.debug_request(tid)
                    if out is None:
                        self._send(404, {
                            "error": f"no recorded events for trace id "
                                     f"{tid!r} (finished long ago, "
                                     "evicted from the ring, or never "
                                     "seen)",
                        }, headers=rid_hdr)
                    else:
                        self._send(200, out)
                else:
                    self._send(404, {"error": "not found"},
                               headers=rid_hdr)
            else:
                self._send(404, {"error": "not found"},
                           headers=rid_hdr)

        def _handle_profile(self, rid_hdr: dict):
            """POST /debug/profile?seconds=N — on-demand jax.profiler
            capture on the live engine."""
            if not server.debug_enabled:
                self._send(404, {"error": "debug endpoints disabled "
                                          "(serve --no-debug)"},
                           headers=rid_hdr)
                return
            qs = urllib.parse.urlsplit(self.path).query
            params = urllib.parse.parse_qs(qs)
            try:
                seconds = float(params.get("seconds", ["2"])[0])
                out = server.profile(seconds)
                if params.get("report", ["0"])[0] not in ("0", ""):
                    # ?report=1: inline the trace-report summary of
                    # the capture just taken — one round trip from
                    # "profile it" to "where did the time go".
                    try:
                        out["report"] = server._analyze_capture(
                            out["trace_dir"])
                    except Exception as e:  # noqa: BLE001 — the
                        # capture itself succeeded; report best-effort
                        out["report"] = {
                            "error": f"{type(e).__name__}: {e}"}
                self._send(200, out, headers=rid_hdr)
            except ProfileInProgress as e:
                self._send(409, {"error": str(e)}, headers=rid_hdr)
            except ValueError as e:
                self._send(400, {"error": str(e)}, headers=rid_hdr)
            except RuntimeError as e:
                # A profiler backend fault (another process-global
                # trace active, unwritable dir) is a server error.
                self._send(500, {"error": str(e)}, headers=rid_hdr)

        def _handle_incident(self, tctx: Tuple[str, int],
                             rid_hdr: dict):
            """POST /debug/incident — manual evidence bundle."""
            if not server.debug_enabled:
                self._send(404, {"error": "debug endpoints disabled "
                                          "(serve --no-debug)"},
                           headers=rid_hdr)
                return
            if server.incidents is None:
                self._send(400, {"error": "incident bundles need "
                                          "serve --incident-dir"},
                           headers=rid_hdr)
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(payload, dict):
                    raise ValueError("payload must be a JSON object")
                seconds = payload.get("seconds")
                if seconds is not None:
                    seconds = float(seconds)
            except (TypeError, ValueError) as e:
                # TypeError: a list/dict "seconds" — a malformed
                # payload must 400, not drop the connection.
                self._send(400, {"error": f"bad incident payload: {e}"},
                           headers=rid_hdr)
                return
            detail = {"via": "POST /debug/incident"}
            if payload.get("note") is not None:
                detail["note"] = str(payload["note"])[:1024]
            errors_before = server.incidents.write_errors
            bid = server.trigger_incident(
                "manual", trace_id=tctx[0], detail=detail,
                # Explicit opt-in only: a bare manual trigger must
                # not inherit the wedge-path auto-capture default.
                capture_seconds=seconds if seconds is not None else 0,
            )
            if bid is None:
                if server.incidents.write_errors > errors_before:
                    # The bundle write FAILED (full disk, bad
                    # permissions): a server fault, not backpressure
                    # — a 429 would tell the operator to wait for a
                    # disk that will never empty itself.
                    self._send(500, {
                        "error": "incident bundle write failed "
                                 "(check --incident-dir "
                                 "permissions/space)",
                    }, headers=rid_hdr)
                    return
                # The sliding-window limiter dropped it: backpressure,
                # not failure — same contract as admission 429.
                self._send(429, {
                    "error": "incident trigger rate-limited "
                             "(--incident-rate per --incident-window)",
                }, headers={
                    **rid_hdr,
                    "Retry-After": str(max(1, int(round(
                        retry_after(2.0, 6.0))))),
                })
                return
            self._send(200, {
                "incident": bid,
                "manifest": (server.incidents.load(bid) or {}).get(
                    "manifest"),
            }, headers=rid_hdr)

        def _stream(self, payload: dict, tctx: Tuple[str, int],
                    tenant: Optional[str] = None):
            # Newline-delimited JSON, no Content-Length: the connection
            # closes at the end of the stream (HTTP/1.0 semantics of
            # BaseHTTPRequestHandler — no keep-alive to preserve).
            lines = server.handle_stream(payload, trace_ctx=tctx,
                                         tenant=tenant)
            try:
                first = next(lines)  # parse errors surface before 200
            except StopIteration:
                first = None
            self.send_response(200)
            self.send_header("Content-Type", "application/x-ndjson")
            self.send_header(REQUEST_ID_HEADER, tctx[0])
            self.end_headers()
            rest = (
                itertools.chain([first], lines) if first is not None else lines
            )
            try:
                for obj in rest:
                    self.wfile.write((json.dumps(obj) + "\n").encode())
                    self.wfile.flush()
            except OSError:
                # Client hung up mid-stream (the normal cancel path);
                # nothing to report and nobody left to report it to.
                pass
            except (ValueError, TimeoutError, RuntimeError) as e:
                # Headers are gone; report in-band and close. The
                # record carries type + retryable + the trace id so a
                # fronting router that has not yet forwarded bytes can
                # classify it, and the client's capture alone
                # identifies the request server-side.
                try:
                    self.wfile.write(
                        (json.dumps(stream_error_payload(
                            e, trace_id=tctx[0])) + "\n")
                        .encode()
                    )
                except OSError:
                    pass

        def _stream_sse(self, payload: dict, chat: bool,
                        tctx: Tuple[str, int],
                        tenant: Optional[str] = None):
            # OpenAI Server-Sent Events framing: one `data: <json>` line
            # per chunk, blank-line separated, closed by `data: [DONE]`.
            chunks = server.handle_openai_stream(payload, chat,
                                                 trace_ctx=tctx,
                                                 tenant=tenant)
            try:
                first = next(chunks, None)  # errors surface before 200
            except (ValueError, TimeoutError) as e:
                self._send(400, {"error": {"message": str(e),
                                           "type": "invalid_request_error"}},
                           headers={REQUEST_ID_HEADER: tctx[0]})
                return
            except ServerUnavailable as e:
                self._send_unavailable(e, openai=True, trace_id=tctx[0])
                return
            except RuntimeError as e:
                # Scheduler death is a server fault, not a bad request.
                self._send(500, {"error": {"message": str(e),
                                           "type": "server_error"}},
                           headers={REQUEST_ID_HEADER: tctx[0]})
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header(REQUEST_ID_HEADER, tctx[0])
            self.end_headers()
            rest = (
                itertools.chain([first], chunks) if first is not None
                else chunks
            )
            try:
                for obj in rest:
                    self.wfile.write(
                        f"data: {json.dumps(obj)}\n\n".encode()
                    )
                    self.wfile.flush()
                self.wfile.write(b"data: [DONE]\n\n")
            except OSError:
                pass  # client hung up: the engine-side cancel fires
            except (ValueError, TimeoutError, RuntimeError) as e:
                try:
                    payload = stream_error_payload(e, trace_id=tctx[0])
                    self.wfile.write(
                        f"data: {json.dumps(payload)}\n\n".encode()
                    )
                except OSError:
                    pass

        def do_POST(self):
            # Trace adoption: the tier (or any front-end) hands the
            # request its distributed trace id + attempt number via
            # x-shellac-trace; direct callers get a freshly minted id.
            # Every response echoes it as x-request-id.
            tctx = adopt_trace(self.headers.get(TRACE_HEADER))
            rid_hdr = {REQUEST_ID_HEADER: tctx[0]}
            # Tenant identity: the explicit header wins everywhere;
            # OpenAI routes additionally fall back to the `user`
            # payload field inside the facade.
            tenant = (self.headers.get(TENANT_HEADER) or "").strip() or None
            if self.path.startswith("/debug/profile"):
                self._handle_profile(rid_hdr)
                return
            if self.path == "/debug/incident":
                # Manual incident trigger: snapshot the evidence NOW.
                # Body (optional): {"note": ..., "seconds": N} — N
                # arms a bounded profiler capture into the bundle.
                self._handle_incident(tctx, rid_hdr)
                return
            if self.path == "/kv/import":
                # Binary KV-migration blob from a prefill replica —
                # handled before the JSON parse below.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    out = server.import_kv(self.rfile.read(n),
                                           trace_ctx=tctx)
                    self._send(200, out, headers=rid_hdr)
                except ValueError as e:
                    self._send(400, {"error": str(e)}, headers=rid_hdr)
                except ServerUnavailable as e:
                    self._send_unavailable(e, trace_id=tctx[0])
                except RuntimeError as e:
                    self._send(500, {"error": str(e)}, headers=rid_hdr)
                return
            if self.path == "/kv/seed":
                # Binary prefix-seed blob (fabric replication) —
                # binary like /kv/import, handled before the JSON
                # parse below.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    out = server.seed_kv(self.rfile.read(n),
                                         trace_ctx=tctx)
                    self._send(200, out, headers=rid_hdr)
                except ValueError as e:
                    self._send(400, {"error": str(e)}, headers=rid_hdr)
                except ServerUnavailable as e:
                    self._send_unavailable(e, trace_id=tctx[0])
                except RuntimeError as e:
                    self._send(500, {"error": str(e)}, headers=rid_hdr)
                return
            if self.path == "/kv/push":
                # Replication order from the tier: export one cached
                # chain and ship it to a peer's /kv/seed.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError(
                            "kv push payload must be a JSON object"
                        )
                    out = server.push_chain(payload, trace_ctx=tctx)
                    self._send(200, out, headers=rid_hdr)
                except ValueError as e:
                    self._send(400, {"error": str(e)}, headers=rid_hdr)
                except ServerUnavailable as e:
                    self._send_unavailable(e, trace_id=tctx[0])
                except RuntimeError as e:
                    self._send(500, {"error": str(e)}, headers=rid_hdr)
                return
            if self.path == "/drain":
                # Admin surface: begin (or with {"resume": true},
                # cancel) a graceful drain. Returns the health
                # snapshot; callers poll /health until `pending`
                # reaches 0, then stop the replica — zero drops.
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                except ValueError:
                    payload = None
                if not isinstance(payload, dict):
                    self._send(400, {"error": "bad drain payload"},
                               headers=rid_hdr)
                    return
                self._send(200, server.resume_admission()
                           if payload.get("resume") else server.drain(),
                           headers=rid_hdr)
                return
            openai_routes = {
                "/v1/completions": False,
                "/v1/chat/completions": True,
            }
            if self.path not in ("/generate", *openai_routes):
                self._send(404, {"error": "not found"}, headers=rid_hdr)
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path in openai_routes:
                    chat = openai_routes[self.path]
                    if payload.get("stream"):
                        self._stream_sse(payload, chat, tctx,
                                         tenant=tenant)
                    else:
                        self._send(200,
                                   server.handle_openai(
                                       payload, chat, trace_ctx=tctx,
                                       tenant=tenant),
                                   headers=rid_hdr)
                elif payload.get("stream"):
                    self._stream(payload, tctx, tenant=tenant)
                else:
                    self._send(200,
                               server.handle(payload, trace_ctx=tctx,
                                             tenant=tenant),
                               headers=rid_hdr)
            except (ValueError, TimeoutError) as e:
                err = {"error": str(e)}
                if self.path in openai_routes:
                    # OpenAI clients expect the nested error shape.
                    err = {"error": {"message": str(e),
                                     "type": "invalid_request_error"}}
                self._send(400, err, headers=rid_hdr)
            except ServerUnavailable as e:
                # Backpressure, not failure: 429 (over the pending cap)
                # or 503 (recovering), each with Retry-After — before
                # the RuntimeError arm, which would misreport it as an
                # opaque 500.
                self._send_unavailable(e, openai=self.path in openai_routes,
                                       trace_id=tctx[0])
            except RuntimeError as e:
                self._send(500, {"error": str(e)}, headers=rid_hdr)

    return ThreadingHTTPServer((host, port), Handler)


def serve(cfg: ModelConfig, params, *, host="127.0.0.1", port=8000,
          tokenizer=None, **engine_kw):
    """Blocking entry point used by the CLI."""
    srv = InferenceServer(cfg, params, tokenizer=tokenizer, **engine_kw)
    httpd = make_http_server(srv, host, port)
    print(json.dumps({"serving": f"http://{host}:{httpd.server_address[1]}"}),
          flush=True)
    try:
        httpd.serve_forever()
    finally:
        srv.close()
