"""Minimal HTTP serving on top of the continuous-batching engine.

Stdlib-only (`http.server`): one scheduler thread owns the
BatchingEngine and is the ONLY thing touching JAX; request handler
threads just enqueue work and wait on per-request events. POSTs block
until their request completes — the concurrency lives in the slot
batch, not in the HTTP layer.

API:
  POST /generate  {"tokens": [1,2,3] | "text": "...", "max_new": 32,
                   "stop": [[7,8], "..."]?}
                  -> {"id", "tokens", "text"?}
  GET  /health    -> {"ok": true, "pending": N}
  GET  /stats     -> engine counters (requests/tokens/steps/prefills,
                     slots busy, decode_ticks)
"""

from __future__ import annotations

import itertools
import json
import queue
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Optional

import numpy as np

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.batching import BatchingEngine


class _Pending:
    __slots__ = ("event", "result", "error")

    def __init__(self):
        self.event = threading.Event()
        self.result = None
        self.error: Optional[str] = None


class InferenceServer:
    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        tokenizer=None,
        engine: Optional[BatchingEngine] = None,
        **engine_kw,
    ):
        self.engine = engine or BatchingEngine(cfg, params, **engine_kw)
        self.tokenizer = tokenizer
        self._submit_q: queue.Queue = queue.Queue()
        self._pending: Dict[int, _Pending] = {}
        self._ids = itertools.count()
        self._stop = threading.Event()
        self._fatal: Optional[str] = None
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # ---- scheduler thread (sole owner of the engine) ----------------

    def _loop(self):
        try:
            self._run()
        except BaseException as e:  # noqa: BLE001
            # The scheduler thread is the only consumer; if it dies
            # silently every pending and future request blocks forever.
            # Fail everything loudly instead.
            self._fatal = f"scheduler died: {type(e).__name__}: {e}"
            self._stop.set()
            for p in list(self._pending.values()):
                p.error = self._fatal
                p.event.set()
            self._pending.clear()
            while True:
                try:
                    rid, *_ = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                p = self._pending.pop(rid, None)
                if p is not None:
                    p.error = self._fatal
                    p.event.set()

    def _run(self):
        while not self._stop.is_set():
            drained = False
            while True:
                try:
                    rid, tokens, max_new, stop = self._submit_q.get_nowait()
                except queue.Empty:
                    break
                drained = True
                try:
                    self.engine.submit(rid, tokens, max_new, stop=stop)
                except ValueError as e:
                    p = self._pending.pop(rid)
                    p.error = str(e)
                    p.event.set()
            if self.engine.pending:
                for rid, out in self.engine.step():
                    p = self._pending.pop(rid, None)
                    if p is not None:
                        p.result = out
                        p.event.set()
            elif not drained:
                # Idle: block briefly on the queue instead of spinning.
                try:
                    item = self._submit_q.get(timeout=0.05)
                    self._submit_q.put(item)
                except queue.Empty:
                    pass

    # ---- client surface ---------------------------------------------

    def generate(self, tokens, max_new: int, timeout: Optional[float] = None,
                 stop=None):
        if self._fatal is not None:
            raise RuntimeError(self._fatal)
        rid = next(self._ids)
        p = _Pending()
        self._pending[rid] = p
        self._submit_q.put((rid, np.asarray(tokens, np.int32), max_new, stop))
        if self._fatal is not None and not p.event.is_set():
            # Scheduler died while we enqueued; its sweep may have
            # missed this request — fail it ourselves.
            self._pending.pop(rid, None)
            raise RuntimeError(self._fatal)
        if not p.event.wait(timeout):
            raise TimeoutError(f"request {rid} timed out")
        if p.error is not None:
            # Scheduler death is a server fault (HTTP 500), not a bad
            # request (400): keep the error classes distinct.
            if self._fatal is not None and p.error == self._fatal:
                raise RuntimeError(p.error)
            raise ValueError(p.error)
        return p.result

    def handle(self, payload: dict) -> dict:
        if "tokens" in payload:
            tokens = np.asarray(payload["tokens"], np.int32)
        elif "text" in payload:
            if self.tokenizer is None:
                raise ValueError('"text" needs a server-side tokenizer')
            tokens = self.tokenizer.encode(payload["text"])
        else:
            raise ValueError('need "tokens" or "text"')
        max_new = int(payload.get("max_new", 32))
        stop = payload.get("stop")
        if stop is not None:
            try:
                parsed = []
                for s in stop:
                    if isinstance(s, str):
                        if self.tokenizer is None:
                            raise ValueError(
                                "string stop sequences need a server-side "
                                "tokenizer"
                            )
                        parsed.append(
                            list(map(int, self.tokenizer.encode(s)))
                        )
                    else:
                        parsed.append(list(map(int, s)))
            except (TypeError, ValueError) as e:
                # Malformed payloads must surface as HTTP 400, not a
                # dropped connection.
                raise ValueError(f"bad stop sequences: {e}")
            stop = parsed
        out = self.generate(
            tokens, max_new, timeout=payload.get("timeout"), stop=stop
        )
        result: Dict[str, Any] = {"tokens": out}
        if self.tokenizer is not None:
            result["text"] = self.tokenizer.decode(out)
        return result

    def close(self):
        self._stop.set()
        self._thread.join(timeout=2)


def make_http_server(server: InferenceServer, host: str = "127.0.0.1",
                     port: int = 0) -> ThreadingHTTPServer:
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, obj: dict):
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._send(200, {"ok": True,
                                 "pending": server.engine.pending})
            elif self.path == "/stats":
                eng = server.engine
                self._send(200, {
                    **eng.stats,
                    "pending": eng.pending,
                    "slots_busy": sum(r is not None for r in eng._slots),
                    "n_slots": eng.n_slots,
                    "decode_ticks": eng.decode_ticks,
                })
            else:
                self._send(404, {"error": "not found"})

        def do_POST(self):
            if self.path != "/generate":
                self._send(404, {"error": "not found"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                self._send(200, server.handle(payload))
            except (ValueError, TimeoutError) as e:
                self._send(400, {"error": str(e)})
            except RuntimeError as e:
                self._send(500, {"error": str(e)})

    return ThreadingHTTPServer((host, port), Handler)


def serve(cfg: ModelConfig, params, *, host="127.0.0.1", port=8000,
          tokenizer=None, **engine_kw):
    """Blocking entry point used by the CLI."""
    srv = InferenceServer(cfg, params, tokenizer=tokenizer, **engine_kw)
    httpd = make_http_server(srv, host, port)
    print(json.dumps({"serving": f"http://{host}:{httpd.server_address[1]}"}),
          flush=True)
    try:
        httpd.serve_forever()
    finally:
        srv.close()
