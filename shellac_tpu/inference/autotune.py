"""Startup auto-tuning of the decode window length and the prefill
chunk size, and the simulated host-latency harness that lets CPU CI
reproduce the relay-bound regime.

BENCH_DECODE measured the serving engine at ~88 ms/tick with ~2 ms of
device work: the tick is host-RPC-bound, so `decode_ticks` (K decode
steps per host sync) is the highest-leverage knob — and its best value
depends entirely on where the host sits relative to the device (local
CPU: 1-2; a relay-attached TPU: 8+). TACCL's lesson (PAPERS.md) applies:
treat the schedule parameter as a first-class searchable object, not a
constant. `autotune_decode_ticks` runs the bench_decode sweep's core —
probe requests through the LIVE engine at each candidate K, measured
wall-clock — once at serving startup, writes the winner back, and
restores the engine to its pre-probe state (PRNG key included) so a
seeded deployment stays reproducible.

`autotune_prefill_chunk` applies the same stance to the admission
side: the chunked-prefill size is the TTFT-vs-TPOT fairness knob
(whole prompts minimize the long request's TTFT but stall every
decoder; small chunks invert it), and which side wins depends on the
latency profile — so it is measured on a mixed workload per
candidate, not guessed.

`SimulatedHostLatency` is the sleep-injected RPC shim the perf
regression gate runs on CPU: it models a remote device whose window
results become available `device_s` after dispatch, whose prefill
results become available `prefill_s` after theirs, and whose dispatch
RPC blocks the host for `dispatch_s`, using the engine's window and
prefill hooks — the real pipeline runs underneath, only the clock is
shaped. With it, overlapped dispatch (decode AND prefill) shows the
same ~max(host, device) vs host+device win on a laptop CPU that it
shows against the relay.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

#: Candidate window lengths swept by default: the bench_decode sweep's
#: range, capped where per-token latency jitter starts to hurt serving.
DEFAULT_CANDIDATES: Tuple[int, ...] = (1, 2, 4, 8, 16)


@dataclass
class AutotuneResult:
    """One decode_ticks sweep: the winner and the per-candidate
    evidence (tokens/s as measured, wall seconds of the timed region)."""

    best: int
    measurements: Dict[int, float] = field(default_factory=dict)  # K -> tok/s
    elapsed: Dict[int, float] = field(default_factory=dict)  # K -> seconds

    def summary(self) -> Dict[str, object]:
        return {
            "decode_ticks": self.best,
            "candidates": {
                str(k): round(v, 1) for k, v in self.measurements.items()
            },
        }


class SimulatedHostLatency:
    """Shape an engine's decode-window AND prefill clocks like a
    remote device.

    Installed via the engine's `_window_hooks` seam (and, when
    `prefill_s` is set, its `_prefill_hooks` twin):

      - `on_dispatch(window)`: sleeps `dispatch_s` (a host-blocking
        submit RPC) and stamps when the window's results will be
        "ready" (`device_s` after dispatch — the simulated device/fetch
        round trip).
      - `before_sync(window)`: sleeps out whatever of `device_s` the
        host has not already spent elsewhere — exactly the wait a real
        device_get would block for.
      - `on_prefill_dispatch(flight)` / `before_prefill_sync(flights)`:
        the same clock shaping for prefill programs — each flight's
        results become available `prefill_s` after its dispatch, so an
        inline (non-overlapped) settle blocks the admission for the
        full round trip while the overlapped batched settle pays only
        whatever of it the host has not already spent on other work.

    The real jitted programs still run (their CPU time happens inside
    the window span, like real device time); only the availability
    clock is stretched. Overlapped dispatch hides host work inside
    `device_s`/`prefill_s`; strict ordering pays host + device
    serially — the measurable contrast the perf gate asserts on.
    """

    def __init__(self, engine, *, device_s: float = 0.0,
                 dispatch_s: float = 0.0, prefill_s: float = 0.0,
                 prefill_token_s: float = 0.0):
        self.engine = engine
        self.device_s = float(device_s)
        self.dispatch_s = float(dispatch_s)
        self.prefill_s = float(prefill_s)
        # Per-TOKEN prefill cost on top of the fixed per-flight
        # prefill_s, charged only for tokens the prefill actually
        # computes (prompt length minus the backend's prefix-cache
        # offset) — the knob that lets a CPU bench show prefix-cache
        # and fabric-seed savings as wall-clock, the way a real device
        # would.
        self.prefill_token_s = float(prefill_token_s)
        self._ready: Dict[int, float] = {}
        engine._window_hooks = self
        if self.prefill_s or self.prefill_token_s:
            engine._prefill_hooks = self

    def on_dispatch(self, window) -> None:
        if self.dispatch_s:
            time.sleep(self.dispatch_s)
        self._ready[id(window)] = time.monotonic() + self.device_s

    def before_sync(self, window) -> None:
        ready = self._ready.pop(id(window), None)
        if ready is not None:
            delay = ready - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    def on_prefill_dispatch(self, flight) -> None:
        if self.dispatch_s:
            time.sleep(self.dispatch_s)
        cost = self.prefill_s
        if self.prefill_token_s:
            computed = flight.req.tokens.size
            try:
                computed -= self.engine.cache_backend.prefill_offset(
                    flight.slot)
            except Exception:  # noqa: BLE001 — backends without the
                pass          # hook charge the full prompt
            cost += self.prefill_token_s * max(0, computed)
        self._ready[id(flight)] = time.monotonic() + cost

    def before_prefill_sync(self, flights) -> None:
        # The batched settle becomes available when the LAST of its
        # flights does; already-elapsed host time is not re-paid.
        ready = [r for r in (self._ready.pop(id(fl), None)
                             for fl in flights) if r is not None]
        if ready:
            delay = max(ready) - time.monotonic()
            if delay > 0:
                time.sleep(delay)

    def uninstall(self) -> None:
        if self.engine._window_hooks is self:
            self.engine._window_hooks = None
        if getattr(self.engine, "_prefill_hooks", None) is self:
            self.engine._prefill_hooks = None
        self._ready.clear()


def autotune_decode_ticks(
    engine,
    *,
    candidates: Sequence[int] = DEFAULT_CANDIDATES,
    probe_windows: int = 3,
    prompt_len: int = 32,
    timer: Callable[[], float] = time.perf_counter,
) -> AutotuneResult:
    """Measure churn tokens/s at each candidate decode_ticks on the
    LIVE engine (its mesh, its compiled model, its real dispatch path)
    and write the winner back via `engine.set_decode_ticks`.

    Per candidate: every slot gets a greedy probe request sized for
    `probe_windows` full windows past a warm-up window (EOS banned via
    min_tokens when the engine has one, so probes cannot end early),
    one un-timed step absorbs the prefills plus the decode-program
    compile, and the drain to completion is timed with `timer` (two
    calls — injectable, so selection is unit-testable with a scripted
    clock). Probes are aborted and the PRNG key restored afterwards:
    a seeded engine leaves the tune exactly as reproducible as it
    entered, and `abort_all` restores allocator state on paged pools.

    Returns the AutotuneResult; `engine.decode_ticks` is the winner and
    `engine.decode_ticks_source` is "auto-tuned".
    """
    if not getattr(engine, "_decode_ticks_tunable", True):
        # Speculative engines pin decode_ticks=1 by contract.
        return AutotuneResult(best=engine.decode_ticks)
    if engine.pending:
        raise RuntimeError(
            "autotune_decode_ticks needs an idle engine (it runs probe "
            "traffic and aborts it); tune before admitting requests"
        )
    candidates = sorted({int(k) for k in candidates})
    if not candidates or candidates[0] < 1:
        raise ValueError(f"bad candidates {candidates!r}: need ints >= 1")
    # Probes must fit the cache (submit's prompt + max_new + 1 bound):
    # shrink the probe prompt on tight caches and drop candidates that
    # still cannot fit, rather than failing serving startup — a replica
    # with a 96-token cache simply tunes over a smaller range.
    prompt_len = min(prompt_len, max(8, engine.max_len // 4))
    candidates = [
        k for k in candidates
        if prompt_len + (1 + probe_windows) * k + 2 <= engine.max_len
    ]
    if not candidates:
        return AutotuneResult(best=engine.decode_ticks)
    rng = np.random.default_rng(0)
    key0 = engine._key
    original = engine.decode_ticks
    result = AutotuneResult(best=original)
    best_rate = -1.0
    # Probe traffic must not leak into serving observability: the tier
    # scores replicas on the very shellac_engine_* gauges and decode-
    # window histograms the sweep would otherwise pollute (a fresh
    # replica would look loaded, with histogram samples taken at the
    # REJECTED candidate K values). Point engine.obs at a disabled
    # scratch registry for the sweep's duration and roll the stats
    # counters back afterwards.
    from shellac_tpu.obs import EngineMetrics, Registry

    stats0 = dict(engine.stats)
    obs0 = engine.obs
    engine.obs = EngineMetrics(Registry(enabled=False))
    try:
        for k in candidates:
            engine.set_decode_ticks(k)
            max_new = (1 + probe_windows) * k + 1
            # Bound re-checked against the submit rule (prompt +
            # max_new + 1 <= max_len) by submit itself below.
            kw = {}
            if engine.eos_id is not None:
                # A probe ending on a sampled EOS would under-measure
                # the candidate; ban EOS for the probe's whole budget.
                kw["min_tokens"] = max_new
            for slot in range(engine.n_slots):
                prompt = rng.integers(
                    0, engine.cfg.vocab_size, size=prompt_len,
                    dtype=np.int64,
                )
                engine.submit(("__autotune__", k, slot), prompt,
                              max_new, **kw)
            # Un-timed: prefills + decode-program compile + first
            # window.
            engine.step()
            tokens0 = engine.stats["tokens_generated"] + sum(
                len(r.out) for r in engine._slots if r is not None
            )
            t0 = timer()
            while engine.pending:
                engine.step()
            t1 = timer()
            tokens1 = engine.stats["tokens_generated"]
            elapsed = max(t1 - t0, 1e-9)
            rate = (tokens1 - tokens0) / elapsed
            result.measurements[k] = rate
            result.elapsed[k] = elapsed
            if rate > best_rate:
                best_rate, result.best = rate, k
    finally:
        engine.abort_all()
        engine._key = key0
        engine.obs = obs0
        engine.stats.clear()
        engine.stats.update(stats0)
    engine.set_decode_ticks(result.best)
    engine.decode_ticks_source = "auto-tuned"
    return result


#: prefill_chunk candidates swept by default: whole prompts (None) vs
#: the chunk sizes a production scheduler actually picks between. The
#: sweep drops candidates larger than the engine's cache.
PREFILL_CHUNK_CANDIDATES: Tuple[Optional[int], ...] = (None, 64, 128,
                                                       256, 512)


@dataclass
class PrefillChunkResult:
    """One prefill_chunk sweep: the winner plus per-candidate evidence
    (mixed-workload tokens/s, and the long prompt's TTFT under each
    candidate — the two sides of the TTFT-vs-TPOT fairness knob, both
    measured rather than guessed)."""

    best: Optional[int]
    measurements: Dict[Optional[int], float] = field(
        default_factory=dict)  # chunk -> tok/s
    ttft: Dict[Optional[int], float] = field(default_factory=dict)

    def summary(self) -> Dict[str, object]:
        return {
            "prefill_chunk": self.best,
            "candidates": {
                str(k): round(v, 1) for k, v in self.measurements.items()
            },
            "long_prompt_ttft_s": {
                str(k): round(v, 4) for k, v in self.ttft.items()
            },
        }


def autotune_prefill_chunk(
    engine,
    *,
    candidates: Sequence[Optional[int]] = PREFILL_CHUNK_CANDIDATES,
    probe_steps: int = 3,
    timer: Callable[[], float] = time.perf_counter,
) -> PrefillChunkResult:
    """Measure a MIXED workload — steady decoders plus a long-prompt
    admission — at each candidate prefill_chunk on the LIVE engine and
    write the winner back via `engine.set_prefill_chunk`.

    This is the TTFT-vs-TPOT fairness knob: whole-prompt prefill
    (None) minimizes the long request's TTFT but stalls every active
    decoder for the whole program; small chunks keep decoders ticking
    but stretch the long prompt's admission. Which side wins depends
    on the host/device latency profile, so — same TVM stance as the
    decode_ticks sweep — it is searched, not guessed. Per candidate:
    all but one slot decode steadily (EOS banned), one long prompt is
    admitted mid-drain, and total generated tokens/s over the timed
    drain decides. The long prompt's TTFT is recorded per candidate as
    evidence. Probes are aborted and the PRNG key restored; a seeded
    deployment stays exactly as reproducible as it entered.
    """
    if not getattr(engine, "_decode_ticks_tunable", True):
        # Speculative engines pin their own prefill discipline (draft
        # and target caches fill in lockstep); nothing to tune.
        return PrefillChunkResult(best=engine.prefill_chunk)
    if engine.pending:
        raise RuntimeError(
            "autotune_prefill_chunk needs an idle engine (it runs "
            "probe traffic and aborts it); tune before admitting "
            "requests"
        )
    if engine.n_slots < 2:
        # The fairness question needs a decoder to stall.
        return PrefillChunkResult(best=engine.prefill_chunk)
    # A long prompt that spans several chunks of the largest surviving
    # candidate, capped so prompt + budget fit the cache.
    ticks = max(1, engine.decode_ticks)
    budget = max(2 * ticks, 8)
    long_len = min(engine.max_len - budget - 2,
                   engine.max_len * 3 // 4)
    if long_len < 32:
        # A cache this tight has no long-prompt problem to tune.
        return PrefillChunkResult(best=engine.prefill_chunk)
    keep: List[Optional[int]] = []
    for c in candidates:
        if c is not None and (c < 1 or c >= long_len):
            continue  # chunk >= prompt degenerates to whole-prompt
        if c not in keep:
            keep.append(c)
    rng = np.random.default_rng(0)
    key0 = engine._key
    chunk0 = engine.prefill_chunk
    result = PrefillChunkResult(best=chunk0)
    best_rate = -1.0
    from shellac_tpu.obs import EngineMetrics, Registry

    stats0 = dict(engine.stats)
    obs0 = engine.obs
    engine.obs = EngineMetrics(Registry(enabled=False))
    try:
        for c in keep:
            try:
                engine.set_prefill_chunk(c)
            except ValueError:
                # Rolling rings cannot grow their chunk slack post-
                # construction; degrade to the surviving range.
                continue
            kw = {}
            if engine.eos_id is not None:
                kw["min_tokens"] = budget + long_len
            # Steady decoders on all but one slot.
            for slot in range(engine.n_slots - 1):
                prompt = rng.integers(0, engine.cfg.vocab_size, size=8,
                                      dtype=np.int64)
                engine.submit(("__chunktune__", str(c), slot), prompt,
                              budget + probe_steps * ticks, **kw)
            engine.step()  # un-timed: prefills + decode compile

            def tokens_seen():
                return engine.stats["tokens_generated"] + sum(
                    len(r.out) for r in engine._slots if r is not None
                )

            rid_long = ("__chunktune__", str(c), "long")
            prompt = rng.integers(0, engine.cfg.vocab_size,
                                  size=long_len, dtype=np.int64)
            tokens0 = tokens_seen()
            t0 = timer()
            engine.submit(rid_long, prompt, 2,
                          **({"min_tokens": 2} if engine.eos_id
                             is not None else {}))
            t_first = None
            while engine.pending:
                done = engine.step()
                if t_first is None:
                    long_req = next(
                        (r for r in engine._slots
                         if r is not None and r.rid == rid_long), None)
                    if ((long_req is not None and long_req.out)
                            or any(rid == rid_long for rid, _ in done)):
                        t_first = timer()
            t1 = timer()
            rate = (tokens_seen() - tokens0) / max(t1 - t0, 1e-9)
            engine.abort_all()  # reset for the next candidate
            result.measurements[c] = rate
            if t_first is not None:
                result.ttft[c] = max(t_first - t0, 0.0)
            if rate > best_rate:
                best_rate, result.best = rate, c
    finally:
        engine.abort_all()
        engine._key = key0
        engine.obs = obs0
        engine.stats.clear()
        engine.stats.update(stats0)
    engine.set_prefill_chunk(result.best)
    engine.prefill_chunk_source = "auto-tuned"
    return result


def maybe_autotune_prefill_chunk(
    engine, log: Optional[Callable[[str], None]] = None, **kw
) -> Optional[PrefillChunkResult]:
    """Tune iff the engine was built with prefill_chunk="auto" and is
    tunable — the serving entry points' one-liner, mirroring
    maybe_autotune. Returns the result, or None when nothing was
    tuned."""
    if getattr(engine, "prefill_chunk_requested", None) != "auto":
        return None
    if not getattr(engine, "_decode_ticks_tunable", True):
        return None
    if hasattr(engine, "is_primary"):
        # Multi-host wrapper: same lockstep constraint as the
        # decode_ticks sweep — pods pin prefill_chunk explicitly.
        return None
    res = autotune_prefill_chunk(engine, **kw)
    if log is not None:
        log(f"prefill_chunk auto-tune: {res.summary()}")
    return res


def maybe_autotune(engine, log: Optional[Callable[[str], None]] = None,
                   **kw) -> Optional[AutotuneResult]:
    """Tune iff the engine was built with decode_ticks="auto" and is
    tunable — the serving entry points' one-liner. Returns the result,
    or None when nothing was tuned."""
    if engine.decode_ticks_requested != "auto":
        return None
    if not getattr(engine, "_decode_ticks_tunable", True):
        return None
    if hasattr(engine, "is_primary"):
        # Multi-host wrapper: probe traffic would have to ride the
        # command broadcast in lockstep with followers that are not
        # serving yet. Pods pin decode_ticks explicitly for now.
        return None
    res = autotune_decode_ticks(engine, **kw)
    if log is not None:
        log(f"decode_ticks auto-tune: {res.summary()}")
    return res


__all__: List[str] = [
    "AutotuneResult",
    "DEFAULT_CANDIDATES",
    "PREFILL_CHUNK_CANDIDATES",
    "PrefillChunkResult",
    "SimulatedHostLatency",
    "autotune_decode_ticks",
    "autotune_prefill_chunk",
    "maybe_autotune",
    "maybe_autotune_prefill_chunk",
]
