"""Structured (grammar-constrained) decoding.

The public Outlines/JSONformer idea — compile a grammar to a finite
automaton over the TOKEN alphabet, then mask logits each step — rebuilt
for this engine's jitted multi-tick decode scan (the reference repo is
empty, SURVEY.md §0; no code is derived from it):

  1. A small regex engine compiles a pattern to a character-level NFA
     (Thompson construction) and determinizes it lazily.
  2. The DFA is lifted to the token alphabet: walking every vocab
     token's string through the character DFA yields one token-level
     transition table `trans (S, V+1) int32` (-1 = disallowed; the
     last column is EOS, allowed exactly in accepting states).
  3. The engine keeps the table on device. Each decode tick does two
     O(1) gathers: `row = trans[state]` masks the logits, and
     `state = row[sampled]` advances — no host sync, so constrained
     decoding rides the same `decode_ticks` scan as everything else
     (inference/batching.py).

JSON-schema support generates a regex for a schema subset (fixed
property order, compact separators) and reuses the same pipeline —
one compiler, one device representation, one masking path.

TPU-first consequences of this design: the per-step work is a gather
+ select (no data-dependent shapes, no host round trip), the table is
built once per (pattern, tokenizer) and cached, and multiple
concurrent constrained requests just stack their tables into one
row-offset table.
"""

from __future__ import annotations

import json
from typing import Dict, FrozenSet, List, Optional, Tuple

import numpy as np

# Compilation guards: a pathological pattern must fail loudly at
# submit time, not hang the scheduler.
MAX_DFA_STATES = 4096


# ---------------------------------------------------------------------------
# regex -> character-level NFA (Thompson construction)
# ---------------------------------------------------------------------------


class _Regex:
    """Recursive-descent parser for a practical regex subset:
    literals, '.', escapes (\\d \\w \\s \\n \\t \\r + punctuation),
    [...] classes with ranges/negation, (...) groups, '|', and the
    postfix operators * + ? {m} {m,} {m,n}. Anchored implicitly (the
    whole output must match the whole pattern)."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        # NFA: transitions[state] = list of (charset | None, target);
        # None = epsilon. charset is a frozenset of single chars.
        self.eps: List[List[int]] = []
        self.edges: List[List[Tuple[FrozenSet[str], int]]] = []

    # -- NFA building blocks --

    def _state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def _frag_char(self, chars: FrozenSet[str]) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        self.edges[a].append((chars, b))
        return a, b

    def _frag_concat(self, f1, f2) -> Tuple[int, int]:
        self.eps[f1[1]].append(f2[0])
        return f1[0], f2[1]

    def _frag_alt(self, frags) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        for f in frags:
            self.eps[a].append(f[0])
            self.eps[f[1]].append(b)
        return a, b

    def _frag_star(self, f) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        self.eps[a] += [f[0], b]
        self.eps[f[1]] += [f[0], b]
        return a, b

    def _frag_eps(self) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        self.eps[a].append(b)
        return a, b

    # -- parsing --

    _CLASSES = {
        "d": frozenset("0123456789"),
        "w": frozenset(
            "abcdefghijklmnopqrstuvwxyz"
            "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
        ),
        "s": frozenset(" \t\n\r\f\v"),
    }
    # '.' excludes newline, standard default.
    _PRINTABLE = frozenset(
        chr(c) for c in range(32, 127)
    ) | frozenset("\t")
    _DOT = _PRINTABLE | frozenset(
        chr(c) for c in range(160, 0x250)
    )  # latin-ish; byte-level tokenizers only ever probe ASCII anyway

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def _next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def _escape(self) -> FrozenSet[str]:
        ch = self._next()
        if ch in self._CLASSES:
            return self._CLASSES[ch]
        if ch in ("D", "W", "S"):
            return frozenset(self._DOT - self._CLASSES[ch.lower()])
        return frozenset({"n": "\n", "t": "\t", "r": "\r",
                          "f": "\f", "v": "\v"}.get(ch, ch))

    def _charclass(self) -> FrozenSet[str]:
        neg = False
        if self._peek() == "^":
            self._next()
            neg = True
        chars: set = set()
        while True:
            ch = self._peek()
            if ch is None:
                raise ValueError(f"unterminated [ in {self.p!r}")
            if ch == "]":
                self._next()
                break
            self._next()
            if ch == "\\":
                sub = self._escape()
                chars |= sub
                continue
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._next()
                hi = self._next()
                if hi == "\\":
                    hi = next(iter(self._escape()))
                chars |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
            else:
                chars.add(ch)
        return frozenset(self._DOT - chars) if neg else frozenset(chars)

    def _repeat(self, frag, lo: int, hi: Optional[int], atom_src):
        """Expand {lo,hi} by cloning the atom (re-parsing the source
        slice — simple and correct for this subset's sizes)."""
        out = self._frag_eps()
        for _ in range(lo):
            out = self._frag_concat(out, self._clone(atom_src))
        if hi is None:
            out = self._frag_concat(out, self._frag_star(self._clone(atom_src)))
        else:
            for _ in range(hi - lo):
                opt = self._clone(atom_src)
                a, b = self._frag_eps()
                self.eps[a].append(opt[0])
                self.eps[opt[1]].append(b)
                out = self._frag_concat(out, (a, b))
        return out

    def _clone(self, src: str):
        save_p, save_i = self.p, self.i
        self.p, self.i = src, 0
        frag = self._parse_alt()
        self.p, self.i = save_p, save_i
        return frag

    def _parse_atom(self):
        start_i = self.i
        ch = self._next()
        if ch == "(":
            frag = self._parse_alt()
            if self._peek() != ")":
                raise ValueError(f"unbalanced ( in {self.p!r}")
            self._next()
        elif ch == "[":
            frag = self._frag_char(self._charclass())
        elif ch == ".":
            frag = self._frag_char(frozenset(self._DOT))
        elif ch == "\\":
            frag = self._frag_char(self._escape())
        elif ch in ")|*+?{":
            raise ValueError(f"unexpected {ch!r} at {self.i} in {self.p!r}")
        else:
            frag = self._frag_char(frozenset(ch))
        return frag, self.p[start_i:self.i]

    def _parse_concat(self):
        frag = self._frag_eps()
        while self._peek() not in (None, "|", ")"):
            atom, src = self._parse_atom()
            ch = self._peek()
            if ch == "*":
                self._next()
                atom = self._frag_star(atom)
            elif ch == "+":
                self._next()
                atom = self._frag_concat(atom, self._frag_star(self._clone(src)))
            elif ch == "?":
                self._next()
                a, b = self._frag_eps()
                self.eps[a].append(atom[0])
                self.eps[atom[1]].append(b)
                atom = (a, b)
            elif ch == "{":
                self._next()
                spec = ""
                while self._peek() not in (None, "}"):
                    spec += self._next()
                if self._peek() != "}":
                    raise ValueError(f"unterminated {{ in {self.p!r}")
                self._next()
                if "," in spec:
                    lo_s, hi_s = spec.split(",", 1)
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else None
                else:
                    lo = hi = int(spec)
                atom = self._repeat(None, lo, hi, src)
            frag = self._frag_concat(frag, atom)
        return frag

    def _parse_alt(self):
        frags = [self._parse_concat()]
        while self._peek() == "|":
            self._next()
            frags.append(self._parse_concat())
        return frags[0] if len(frags) == 1 else self._frag_alt(frags)

    def compile(self):
        frag = self._parse_alt()
        if self.i != len(self.p):
            raise ValueError(f"trailing {self.p[self.i:]!r} in {self.p!r}")
        return frag


class CharDFA:
    """Lazily-determinized character automaton over the NFA."""

    def __init__(self, pattern: str):
        rx = _Regex(pattern)
        start, accept = rx.compile()
        self._eps = rx.eps
        self._edges = rx.edges
        self._accept_nfa = accept
        self.start = self._closure(frozenset({start}))
        self._memo: Dict[Tuple[FrozenSet[int], str], Optional[FrozenSet[int]]] = {}

    def _closure(self, states: FrozenSet[int]) -> FrozenSet[int]:
        out, stack = set(states), list(states)
        while stack:
            s = stack.pop()
            for t in self._eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def step(self, state: FrozenSet[int], ch: str) -> Optional[FrozenSet[int]]:
        key = (state, ch)
        if key in self._memo:
            return self._memo[key]
        nxt = set()
        for s in state:
            for chars, t in self._edges[s]:
                if ch in chars:
                    nxt.add(t)
        res = self._closure(frozenset(nxt)) if nxt else None
        self._memo[key] = res
        return res

    def accepting(self, state: FrozenSet[int]) -> bool:
        return self._accept_nfa in state


# ---------------------------------------------------------------------------
# token-level lifting
# ---------------------------------------------------------------------------


class TokenDFA:
    """Token-level automaton: trans (S, V+1) int32, -1 = disallowed.

    Column V (the last) is the EOS column: allowed exactly in
    accepting states (its target is the state itself; the engine
    finishes the request on EOS as usual). Built by BFS over the
    character DFA — each discovered state walks every token's string.
    """

    def __init__(self, trans: np.ndarray, eos_id: int):
        self.trans = trans
        self.eos_id = eos_id

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]


def _token_strings(tokenizer, vocab_size: int,
                   eos_id: int) -> List[Optional[str]]:
    """Decode each id to its surface string; None disables the token
    (specials, undecodable, and EOS itself — EOS is the dedicated
    last column)."""
    out: List[Optional[str]] = []
    for tid in range(vocab_size):
        if tid == eos_id:
            out.append(None)
            continue
        try:
            s = tokenizer.decode([tid])
        except Exception:
            out.append(None)
            continue
        out.append(s if s else None)
    return out


def compile_token_dfa(pattern: str, tokenizer, vocab_size: int,
                      eos_id: int) -> TokenDFA:
    """pattern -> TokenDFA over this tokenizer's vocab.

    eos_id comes from the caller (the engine's configured EOS), not
    sniffed off the tokenizer — the two must agree or EOS masking
    would silently diverge from request termination.

    Cache externally on (pattern, id(tokenizer)) — the engine does.
    """
    cdfa = CharDFA(pattern)
    toks = _token_strings(tokenizer, vocab_size, eos_id)

    states: Dict[FrozenSet[int], int] = {cdfa.start: 0}
    order: List[FrozenSet[int]] = [cdfa.start]
    rows: List[np.ndarray] = []
    qi = 0
    while qi < len(order):
        st = order[qi]
        qi += 1
        row = np.full((vocab_size + 1,), -1, np.int32)
        for tid, s in enumerate(toks):
            if s is None:
                continue
            cur = st
            for ch in s:
                cur = cdfa.step(cur, ch)
                if cur is None:
                    break
            if cur is None:
                continue
            if cur not in states:
                if len(states) >= MAX_DFA_STATES:
                    raise ValueError(
                        f"constraint DFA exceeds {MAX_DFA_STATES} "
                        f"states; simplify the pattern"
                    )
                states[cur] = len(order)
                order.append(cur)
            row[tid] = states[cur]
        if cdfa.accepting(st):
            row[vocab_size] = states[st]  # EOS allowed, self-loop
        rows.append(row)
    trans = np.stack(rows, axis=0)
    # A state from which nothing (not even EOS) is allowed would wedge
    # a slot; they are unreachable in well-formed patterns but guard
    # anyway.
    dead = ~(trans >= 0).any(axis=1)
    if dead.any():
        raise ValueError("constraint DFA contains dead states")
    return TokenDFA(trans, eos_id)


# ---------------------------------------------------------------------------
# JSON schema -> regex
# ---------------------------------------------------------------------------

_STR = r'"[^"\\]*"'  # compact strings, no escape sequences
_INT = r"-?(0|[1-9][0-9]*)"
_NUM = _INT + r"(\.[0-9]+)?([eE][-+]?[0-9]+)?"
_BOOL = r"(true|false)"
_NULL = r"null"


def _schema_regex(schema: dict, depth: int = 3) -> str:
    t = schema.get("type")
    if "enum" in schema:
        alts = []
        for v in schema["enum"]:
            alts.append(_escape_literal(json.dumps(v)))
        return "(" + "|".join(alts) + ")"
    if t == "string":
        if "pattern" in schema:
            # Group the user pattern: a top-level '|' must stay scoped
            # to the string body, not split the whole grammar.
            return '"(' + schema["pattern"] + ')"'
        return _STR
    if t == "integer":
        return _INT
    if t == "number":
        return _NUM
    if t == "boolean":
        return _BOOL
    if t == "null":
        return _NULL
    if t == "array":
        if depth <= 0:
            raise ValueError("schema nests deeper than supported")
        item = _schema_regex(schema.get("items", {}), depth - 1)
        return r"\[(" + item + r"(," + item + r")*)?\]"
    if t == "object" or "properties" in schema:
        if depth <= 0:
            raise ValueError("schema nests deeper than supported")
        props = schema.get("properties", {})
        if not props:
            # Free-form object: depth-limited generic JSON.
            return _generic_json_regex(depth - 1, kind="object")
        parts = []
        for name, sub in props.items():
            key = _escape_literal(json.dumps(name))
            parts.append(key + ":" + _schema_regex(sub, depth - 1))
        # Fixed property order (the public structured-output norm for
        # regex-compiled schemas), compact separators, all properties
        # present.
        return r"\{" + ",".join(parts) + r"\}"
    if t is None and not schema:
        return _generic_json_regex(depth - 1, kind="value")
    raise ValueError(f"unsupported schema fragment: {schema!r}")


def _escape_literal(s: str) -> str:
    return "".join(
        "\\" + c if c in r"\.[]{}()*+?|^$" else c for c in s
    )


def _generic_json_regex(depth: int, kind: str = "value") -> str:
    """Depth-limited generic JSON value (regular approximation of the
    recursive grammar; depth levels of nesting)."""
    scalar = f"({_STR}|{_NUM}|{_BOOL}|{_NULL})"
    value = scalar
    for _ in range(max(depth, 0)):
        obj = r"\{(" + _STR + ":" + value + "(," + _STR + ":" + value + r")*)?\}"
        arr = r"\[(" + value + "(," + value + r")*)?\]"
        value = f"({scalar}|{obj}|{arr})"
    if kind == "object":
        return r"\{(" + _STR + ":" + value + "(," + _STR + ":" + value + r")*)?\}"
    return value


def constraint_pattern(spec: dict) -> str:
    """Normalize a user constraint spec into one regex pattern.

    spec: {"regex": ...} | {"json_schema": {...}} | {"json_object": true}
    (the native API shape; the OpenAI response_format translates onto
    this in the server).
    """
    if not isinstance(spec, dict):
        raise ValueError("constraint must be an object")
    keys = [k for k in ("regex", "json_schema", "json_object") if k in spec]
    if len(keys) != 1:
        raise ValueError(
            "constraint needs exactly one of regex/json_schema/json_object"
        )
    if keys[0] == "regex":
        if not isinstance(spec["regex"], str):
            raise ValueError("constraint.regex must be a string")
        return spec["regex"]
    if keys[0] == "json_schema":
        return _schema_regex(spec["json_schema"])
    return _generic_json_regex(2, kind="object")
