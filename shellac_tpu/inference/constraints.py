"""Structured (grammar-constrained) decoding.

The public Outlines/JSONformer idea — compile a grammar to a finite
automaton over the TOKEN alphabet, then mask logits each step — rebuilt
for this engine's jitted multi-tick decode scan (the reference repo is
empty, SURVEY.md §0; no code is derived from it):

  1. A small regex engine compiles a pattern to an NFA over UTF-8
     BYTES (Thompson construction): character classes are codepoint
     RANGES lowered to byte-sequence range chains (the standard
     UTF-8 range decomposition), so the full Unicode plane — Cyrillic
     enum values, CJK literals, emoji — constrains exactly, and
     byte-level tokenizers whose tokens split multi-byte characters
     advance the automaton mid-character.
  2. The byte NFA is determinized (lazily for the char-level API,
     exhaustively for compilation), MINIMIZED (Moore partition
     refinement over the 256-byte alphabet — counting patterns and
     schema compilations shrink several-fold, which is what raises
     the practical state capacity), then lifted to the token
     alphabet: walking every vocab token's bytes through the byte DFA
     yields one token-level transition table `trans (S, V+1) int32`
     (-1 = disallowed; the last column is EOS, allowed exactly in
     accepting states).
  3. The engine keeps the table on device. Each decode tick does two
     O(1) gathers: `row = trans[state]` masks the logits, and
     `state = row[sampled]` advances — no host sync, so constrained
     decoding rides the same `decode_ticks` scan as everything else
     (inference/batching.py).

JSON-schema support generates a regex for a schema subset — optional
properties (the `required` list is honored; undeclared = optional,
per the JSON-Schema spec), anyOf/oneOf alternation, const/enum with
any Unicode content, nested arrays/objects, local `$ref`
(`#/$defs/...`, cycle-detected), common string `format`s (date-time,
date, uuid, email), and `additionalProperties: true` (extra pairs
append after the declared sequence via the depth-limited generic-JSON
grammar) — and reuses the same pipeline: one compiler, one device
representation, one masking path. Property ORDER stays fixed (the
public structured-output norm for regex-compiled schemas).

TPU-first consequences of this design: the per-step work is a gather
+ select (no data-dependent shapes, no host round trip), the table is
built once per (pattern, tokenizer) and cached, and multiple
concurrent constrained requests just stack their tables into one
row-offset table. The table is DENSE (S x V+1 int32): minimization
plus a total-entries budget (MAX_TABLE_ENTRIES) bound its memory —
the budget, not the state cap alone, is what protects HBM for large
vocabularies.
"""

from __future__ import annotations

import json
from typing import Any, Dict, FrozenSet, List, Optional, Tuple

import numpy as np

# Compilation guards: a pathological pattern must fail loudly at
# submit time, not hang the scheduler or blow HBM.
MAX_DFA_STATES = 8192          # token-level states per constraint
MAX_BYTE_STATES = 65536        # byte-level exploration bound
MAX_TABLE_ENTRIES = 32_000_000  # S * (V+1) budget (~128 MB int32)
# Token-walk precompute budget (vocab x byte-states). Over budget,
# compilation switches to per-state walking — slower per discovered
# state, bounded memory.
MAX_WALK_ENTRIES = 32_000_000

_MAX_CP = 0x10FFFF
# '.' excludes newline (standard default); surrogates are not valid
# codepoints.
_DOT_RANGES = ((0x00, 0x09), (0x0B, 0xD7FF), (0xE000, _MAX_CP))
# The full universe, newline included. Negated classes ([^x]) and the
# complemented escapes (\D \W \S) complement within THIS universe —
# standard regex semantics, where only '.' excludes newline.
_ANY_RANGES = ((0x00, 0xD7FF), (0xE000, _MAX_CP))

Ranges = Tuple[Tuple[int, int], ...]


def _norm_ranges(pairs) -> Ranges:
    """Sort + merge overlapping/adjacent codepoint ranges."""
    pairs = sorted((lo, hi) for lo, hi in pairs if lo <= hi)
    out: List[Tuple[int, int]] = []
    for lo, hi in pairs:
        if out and lo <= out[-1][1] + 1:
            out[-1] = (out[-1][0], max(out[-1][1], hi))
        else:
            out.append((lo, hi))
    return tuple(out)


def _ranges_from_chars(chars) -> Ranges:
    return _norm_ranges((ord(c), ord(c)) for c in chars)


def _intersect(a: Ranges, b: Ranges) -> Ranges:
    out = []
    for lo1, hi1 in a:
        for lo2, hi2 in b:
            lo, hi = max(lo1, lo2), min(hi1, hi2)
            if lo <= hi:
                out.append((lo, hi))
    return _norm_ranges(out)


def _complement(a: Ranges, universe: Ranges = _ANY_RANGES) -> Ranges:
    out = []
    for ulo, uhi in universe:
        cur = ulo
        for lo, hi in a:
            if hi < cur or lo > uhi:
                continue
            if lo > cur:
                out.append((cur, lo - 1))
            cur = max(cur, hi + 1)
            if cur > uhi:
                break
        if cur <= uhi:
            out.append((cur, uhi))
    return _norm_ranges(out)


def _utf8(cp: int) -> bytes:
    return chr(cp).encode("utf-8")


def _utf8_seqs(lo: int, hi: int) -> List[Tuple[Tuple[int, int], ...]]:
    """Decompose a codepoint range into UTF-8 byte-range sequences.

    Returns chains of per-byte (lo, hi) ranges whose concatenated
    byte strings cover exactly the UTF-8 encodings of [lo, hi] — the
    standard decomposition (public: Lucene UTF32ToUTF8 /
    regex-automata), re-derived here."""

    def seq(lo_b: bytes, hi_b: bytes) -> List[Tuple[Tuple[int, int], ...]]:
        n = len(lo_b)
        if n == 1:
            return [((lo_b[0], hi_b[0]),)]
        if lo_b[0] == hi_b[0]:
            return [((lo_b[0], lo_b[0]),) + tail
                    for tail in seq(lo_b[1:], hi_b[1:])]
        mins = bytes([0x80] * (n - 1))
        maxs = bytes([0xBF] * (n - 1))
        res: List[Tuple[Tuple[int, int], ...]] = []
        lo_first = lo_b[0]
        if lo_b[1:] != mins:
            res += [((lo_b[0], lo_b[0]),) + tail
                    for tail in seq(lo_b[1:], maxs)]
            lo_first = lo_b[0] + 1
        hi_first = hi_b[0]
        tail_part: List[Tuple[Tuple[int, int], ...]] = []
        if hi_b[1:] != maxs:
            tail_part = [((hi_b[0], hi_b[0]),) + t
                         for t in seq(mins, hi_b[1:])]
            hi_first = hi_b[0] - 1
        if lo_first <= hi_first:
            res.append(((lo_first, hi_first),)
                       + tuple((0x80, 0xBF) for _ in range(n - 1)))
        return res + tail_part

    out: List[Tuple[Tuple[int, int], ...]] = []
    # Split by encoded length first (1..4 bytes).
    for a, b in ((0x00, 0x7F), (0x80, 0x7FF), (0x800, 0xFFFF),
                 (0x10000, _MAX_CP)):
        s, e = max(lo, a), min(hi, b)
        if s <= e:
            out.extend(seq(_utf8(s), _utf8(e)))
    return out


# ---------------------------------------------------------------------------
# regex -> byte-level NFA (Thompson construction)
# ---------------------------------------------------------------------------


class _Regex:
    """Recursive-descent parser for a practical regex subset:
    literals (full Unicode), '.', escapes (\\d \\w \\s \\n \\t \\r +
    punctuation), [...] classes with ranges/negation, (...) groups,
    '|', and the postfix operators * + ? {m} {m,} {m,n}. Anchored
    implicitly (the whole output must match the whole pattern). The
    NFA alphabet is UTF-8 BYTES: each character-class edge lowers to
    byte-range chains via _utf8_seqs."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0
        # NFA: edges[state] = list of (byte_lo, byte_hi, target);
        # eps[state] = epsilon targets.
        self.eps: List[List[int]] = []
        self.edges: List[List[Tuple[int, int, int]]] = []

    # -- NFA building blocks --

    def _state(self) -> int:
        self.eps.append([])
        self.edges.append([])
        return len(self.eps) - 1

    def _frag_char(self, ranges: Ranges) -> Tuple[int, int]:
        if not ranges:
            raise ValueError(
                f"empty character class in {self.p!r} (negation left "
                "nothing matchable)"
            )
        a, b = self._state(), self._state()
        for lo, hi in ranges:
            for chain in _utf8_seqs(lo, hi):
                cur = a
                for j, (blo, bhi) in enumerate(chain):
                    nxt = b if j == len(chain) - 1 else self._state()
                    self.edges[cur].append((blo, bhi, nxt))
                    cur = nxt
        return a, b

    def _frag_concat(self, f1, f2) -> Tuple[int, int]:
        self.eps[f1[1]].append(f2[0])
        return f1[0], f2[1]

    def _frag_alt(self, frags) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        for f in frags:
            self.eps[a].append(f[0])
            self.eps[f[1]].append(b)
        return a, b

    def _frag_star(self, f) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        self.eps[a] += [f[0], b]
        self.eps[f[1]] += [f[0], b]
        return a, b

    def _frag_eps(self) -> Tuple[int, int]:
        a, b = self._state(), self._state()
        self.eps[a].append(b)
        return a, b

    # -- parsing --

    _CLASSES = {
        "d": _ranges_from_chars("0123456789"),
        "w": _ranges_from_chars(
            "abcdefghijklmnopqrstuvwxyz"
            "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_"
        ),
        "s": _ranges_from_chars(" \t\n\r\f\v"),
    }

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def _next(self) -> str:
        ch = self.p[self.i]
        self.i += 1
        return ch

    def _escape(self) -> Ranges:
        ch = self._next()
        if ch in self._CLASSES:
            return self._CLASSES[ch]
        if ch in ("D", "W", "S"):
            return _complement(self._CLASSES[ch.lower()])
        lit = {"n": "\n", "t": "\t", "r": "\r",
               "f": "\f", "v": "\v"}.get(ch, ch)
        return _ranges_from_chars(lit)

    def _charclass(self) -> Ranges:
        neg = False
        if self._peek() == "^":
            self._next()
            neg = True
        pairs: List[Tuple[int, int]] = []
        while True:
            ch = self._peek()
            if ch is None:
                raise ValueError(f"unterminated [ in {self.p!r}")
            if ch == "]":
                self._next()
                break
            self._next()
            if ch == "\\":
                pairs.extend(self._escape())
                continue
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._next()
                hi = self._next()
                if hi == "\\":
                    sub = self._escape()
                    if len(sub) != 1 or sub[0][0] != sub[0][1]:
                        raise ValueError(
                            f"range endpoint must be a single char in "
                            f"{self.p!r}"
                        )
                    hi_cp = sub[0][0]
                else:
                    hi_cp = ord(hi)
                if ord(ch) > hi_cp:
                    # Standard engines reject [z-a]; silently narrowing
                    # the class would change the constrained language
                    # with no error at submit time.
                    raise ValueError(
                        f"bad character range {ch}-{chr(hi_cp)} in "
                        f"{self.p!r} (reversed endpoints)"
                    )
                pairs.append((ord(ch), hi_cp))
            else:
                pairs.append((ord(ch), ord(ch)))
        ranges = _intersect(_norm_ranges(pairs), _ANY_RANGES)
        return _complement(ranges) if neg else ranges

    def _repeat(self, frag, lo: int, hi: Optional[int], atom_src):
        """Expand {lo,hi} by cloning the atom (re-parsing the source
        slice — simple and correct for this subset's sizes)."""
        out = self._frag_eps()
        for _ in range(lo):
            out = self._frag_concat(out, self._clone(atom_src))
        if hi is None:
            out = self._frag_concat(out, self._frag_star(self._clone(atom_src)))
        else:
            for _ in range(hi - lo):
                opt = self._clone(atom_src)
                a, b = self._frag_eps()
                self.eps[a].append(opt[0])
                self.eps[opt[1]].append(b)
                out = self._frag_concat(out, (a, b))
        return out

    def _clone(self, src: str):
        save_p, save_i = self.p, self.i
        self.p, self.i = src, 0
        frag = self._parse_alt()
        self.p, self.i = save_p, save_i
        return frag

    def _parse_atom(self):
        start_i = self.i
        ch = self._next()
        if ch == "(":
            frag = self._parse_alt()
            if self._peek() != ")":
                raise ValueError(f"unbalanced ( in {self.p!r}")
            self._next()
        elif ch == "[":
            frag = self._frag_char(self._charclass())
        elif ch == ".":
            frag = self._frag_char(_DOT_RANGES)
        elif ch == "\\":
            frag = self._frag_char(self._escape())
        elif ch in ")|*+?{":
            raise ValueError(f"unexpected {ch!r} at {self.i} in {self.p!r}")
        else:
            frag = self._frag_char(_ranges_from_chars(ch))
        return frag, self.p[start_i:self.i]

    def _parse_concat(self):
        frag = self._frag_eps()
        while self._peek() not in (None, "|", ")"):
            atom, src = self._parse_atom()
            ch = self._peek()
            if ch == "*":
                self._next()
                atom = self._frag_star(atom)
            elif ch == "+":
                self._next()
                atom = self._frag_concat(atom, self._frag_star(self._clone(src)))
            elif ch == "?":
                self._next()
                a, b = self._frag_eps()
                self.eps[a].append(atom[0])
                self.eps[atom[1]].append(b)
                atom = (a, b)
            elif ch == "{":
                self._next()
                spec = ""
                while self._peek() not in (None, "}"):
                    spec += self._next()
                if self._peek() != "}":
                    raise ValueError(f"unterminated {{ in {self.p!r}")
                self._next()
                if "," in spec:
                    lo_s, hi_s = spec.split(",", 1)
                    lo = int(lo_s)
                    hi = int(hi_s) if hi_s else None
                else:
                    lo = hi = int(spec)
                atom = self._repeat(None, lo, hi, src)
            frag = self._frag_concat(frag, atom)
        return frag

    def _parse_alt(self):
        frags = [self._parse_concat()]
        while self._peek() == "|":
            self._next()
            frags.append(self._parse_concat())
        return frags[0] if len(frags) == 1 else self._frag_alt(frags)

    def compile(self):
        frag = self._parse_alt()
        if self.i != len(self.p):
            raise ValueError(f"trailing {self.p[self.i:]!r} in {self.p!r}")
        return frag


class CharDFA:
    """Lazily-determinized automaton over the byte NFA.

    The public API stays character-level (`step(state, ch)` walks the
    char's UTF-8 bytes) so callers and tests are alphabet-agnostic;
    `step_byte` exposes the byte granularity the token lifting uses."""

    def __init__(self, pattern: str):
        rx = _Regex(pattern)
        start, accept = rx.compile()
        self._eps = rx.eps
        self._edges = rx.edges
        self._accept_nfa = accept
        self.start = self._closure(frozenset({start}))
        self._memo: Dict[Tuple[FrozenSet[int], int],
                         Optional[FrozenSet[int]]] = {}

    def _closure(self, states: FrozenSet[int]) -> FrozenSet[int]:
        out, stack = set(states), list(states)
        while stack:
            s = stack.pop()
            for t in self._eps[s]:
                if t not in out:
                    out.add(t)
                    stack.append(t)
        return frozenset(out)

    def step_byte(self, state: FrozenSet[int],
                  b: int) -> Optional[FrozenSet[int]]:
        key = (state, b)
        if key in self._memo:
            return self._memo[key]
        nxt = set()
        for s in state:
            for lo, hi, t in self._edges[s]:
                if lo <= b <= hi:
                    nxt.add(t)
        res = self._closure(frozenset(nxt)) if nxt else None
        self._memo[key] = res
        return res

    def step(self, state: FrozenSet[int],
             ch: str) -> Optional[FrozenSet[int]]:
        cur = state
        for b in ch.encode("utf-8"):
            cur = self.step_byte(cur, b)
            if cur is None:
                return None
        return cur

    def accepting(self, state: FrozenSet[int]) -> bool:
        return self._accept_nfa in state


# ---------------------------------------------------------------------------
# explicit byte DFA + minimization
# ---------------------------------------------------------------------------


def _byte_dfa(cdfa: CharDFA) -> Tuple[np.ndarray, np.ndarray]:
    """Exhaustively determinize: (trans (S, 256) int32 with -1 dead,
    accept (S,) bool). State 0 is the start."""
    index: Dict[FrozenSet[int], int] = {cdfa.start: 0}
    order = [cdfa.start]
    rows: List[np.ndarray] = []
    qi = 0
    while qi < len(order):
        st = order[qi]
        qi += 1
        row = np.full((256,), -1, np.int32)
        for b in range(256):
            nxt = cdfa.step_byte(st, b)
            if nxt is None:
                continue
            if nxt not in index:
                if len(index) >= MAX_BYTE_STATES:
                    raise ValueError(
                        f"constraint byte DFA exceeds {MAX_BYTE_STATES} "
                        f"states; simplify the pattern"
                    )
                index[nxt] = len(order)
                order.append(nxt)
            row[b] = index[nxt]
        rows.append(row)
    trans = np.stack(rows, axis=0)
    accept = np.asarray([cdfa.accepting(st) for st in order], bool)
    return trans, accept


def _minimize(trans: np.ndarray,
              accept: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Moore partition refinement over the 256-byte alphabet.

    Equivalent states (same acceptance, transitions into the same
    blocks for every byte) merge; counting patterns and schema
    compilations shrink several-fold, which is what buys token-table
    capacity under the dense-row memory budget."""
    s = trans.shape[0]
    block = accept.astype(np.int64).copy()  # initial split: accepting?
    # Map -1 (dead) to a fixed sentinel block forever.
    while True:
        # Signature: own block + successor blocks per byte.
        succ = np.where(trans >= 0, block[np.clip(trans, 0, None)], -1)
        sig = np.concatenate([block[:, None], succ], axis=1)
        _, new_block = np.unique(sig, axis=0, return_inverse=True)
        if (new_block == block).all() or len(np.unique(new_block)) == s:
            block = new_block
            break
        block = new_block
    n_blocks = int(block.max()) + 1
    # Representative per block; remap start (state 0) to block order
    # with the start's block first for determinism.
    remap = np.full((n_blocks,), -1, np.int64)
    new_ids = []
    next_id = 0
    for st in range(s):
        b = block[st]
        if remap[b] < 0:
            remap[b] = next_id
            new_ids.append(st)
            next_id += 1
    reps = np.asarray(new_ids)
    new_trans = trans[reps]
    new_trans = np.where(
        new_trans >= 0,
        remap[block[np.clip(new_trans, 0, None)]].astype(np.int32),
        -1,
    ).astype(np.int32)
    new_accept = accept[reps]
    return new_trans, new_accept


# ---------------------------------------------------------------------------
# token-level lifting
# ---------------------------------------------------------------------------


class TokenDFA:
    """Token-level automaton: trans (S, V+1) int32, -1 = disallowed.

    Column V (the last) is the EOS column: allowed exactly in
    accepting states (its target is the state itself; the engine
    finishes the request on EOS as usual). Built by BFS over the
    minimized byte DFA — each discovered state walks every token's
    bytes.
    """

    def __init__(self, trans: np.ndarray, eos_id: int):
        self.trans = trans
        self.eos_id = eos_id

    @property
    def n_states(self) -> int:
        return self.trans.shape[0]


def _token_bytes(tokenizer, vocab_size: int,
                 eos_id: int) -> List[Optional[bytes]]:
    """Each id's surface BYTES; None disables the token (specials,
    undecodable, and EOS itself — EOS is the dedicated last column).

    Tokenizers may expose `token_bytes(tid) -> bytes | None` for exact
    byte surfaces (byte-level vocabularies whose tokens split UTF-8
    characters NEED this — decode() replaces partial sequences with
    U+FFFD). The fallback decodes and re-encodes, disabling any token
    whose decode was lossy."""
    has_tb = hasattr(tokenizer, "token_bytes")
    out: List[Optional[bytes]] = []
    for tid in range(vocab_size):
        if tid == eos_id:
            out.append(None)
            continue
        if has_tb:
            try:
                out.append(tokenizer.token_bytes(tid) or None)
            except Exception:
                out.append(None)
            continue
        try:
            s = tokenizer.decode([tid])
        except Exception:
            out.append(None)
            continue
        if not s or "�" in s:
            # Lossy decode: the true bytes are unknowable here.
            out.append(None)
            continue
        out.append(s.encode("utf-8"))
    return out


def compile_token_dfa(pattern: str, tokenizer, vocab_size: int,
                      eos_id: int) -> TokenDFA:
    """pattern -> TokenDFA over this tokenizer's vocab.

    eos_id comes from the caller (the engine's configured EOS), not
    sniffed off the tokenizer — the two must agree or EOS masking
    would silently diverge from request termination.

    Cache externally on (pattern, id(tokenizer)) — the engine does.
    """
    btrans, baccept = _minimize(*_byte_dfa(CharDFA(pattern)))
    toks = _token_bytes(tokenizer, vocab_size, eos_id)

    max_states = min(MAX_DFA_STATES,
                     max(MAX_TABLE_ENTRIES // (vocab_size + 1), 1))

    n_b = btrans.shape[0]
    if vocab_size * n_b <= MAX_WALK_ENTRIES:
        # Fast path: precompute each token's byte-walk over ALL byte
        # states at once (vectorized over states; tokens loop
        # host-side once). walk[tid] maps byte-state -> byte-state
        # after the token (-1 dead).
        walk = np.full((vocab_size, n_b), -1, np.int32)
        ids = np.arange(n_b, dtype=np.int32)
        for tid, bs in enumerate(toks):
            if bs is None:
                continue
            cur = ids
            for b in bs:
                cur = np.where(
                    cur >= 0, btrans[np.clip(cur, 0, None), b], -1
                )
            walk[tid] = cur

        def targets_from(st: int) -> np.ndarray:
            return walk[:, st]
    else:
        # Budget path (huge vocab x many byte states would blow the
        # walk matrix): walk all tokens from ONE state at a time,
        # vectorized over tokens via a padded byte matrix. Only
        # DISCOVERED token states pay this cost.
        lmax = max((len(b) for b in toks if b is not None), default=1)
        tok_mat = np.full((vocab_size, lmax), -1, np.int16)
        for tid, bs in enumerate(toks):
            if bs is None:
                continue
            tok_mat[tid, :len(bs)] = np.frombuffer(bs, np.uint8)
        enabled = np.asarray([b is not None for b in toks], bool)

        def targets_from(st: int) -> np.ndarray:
            cur = np.where(enabled, st, -1).astype(np.int32)
            for j in range(lmax):
                bj = tok_mat[:, j]
                step = np.where(
                    cur >= 0,
                    btrans[np.clip(cur, 0, None), np.clip(bj, 0, None)],
                    -1,
                )
                cur = np.where(bj >= 0, step, cur)
            return cur

    states: Dict[int, int] = {0: 0}
    order: List[int] = [0]
    rows: List[np.ndarray] = []
    qi = 0
    while qi < len(order):
        st = order[qi]
        qi += 1
        tgt = targets_from(st)  # (V,) byte-state after each token
        row = np.full((vocab_size + 1,), -1, np.int32)
        for tid in np.nonzero(tgt >= 0)[0]:
            nxt = int(tgt[tid])
            if nxt not in states:
                if len(states) >= max_states:
                    raise ValueError(
                        f"constraint DFA exceeds {max_states} states "
                        f"(cap {MAX_DFA_STATES}, table budget "
                        f"{MAX_TABLE_ENTRIES} entries at vocab "
                        f"{vocab_size}); simplify the pattern"
                    )
                states[nxt] = len(order)
                order.append(nxt)
            row[tid] = states[nxt]
        if baccept[st]:
            row[vocab_size] = states[st]  # EOS allowed, self-loop
        rows.append(row)
    trans = np.stack(rows, axis=0)
    # A state from which nothing (not even EOS) is allowed would wedge
    # a slot; they are unreachable in well-formed patterns but guard
    # anyway.
    dead = ~(trans >= 0).any(axis=1)
    if dead.any():
        raise ValueError("constraint DFA contains dead states")
    return TokenDFA(trans, eos_id)


# ---------------------------------------------------------------------------
# JSON schema -> regex
# ---------------------------------------------------------------------------

# Compact strings, no escape sequences. Control bytes (0x00-0x1F) are
# excluded explicitly: they live inside the regex engine's negated-
# class universe, but JSON forbids them raw in strings — a constraint-
# conforming output must stay json.loads-able.
_CTRL = "".join(chr(c) for c in range(0x20))
_STR = '"[^"\\\\' + _CTRL + ']*"'
_INT = r"-?(0|[1-9][0-9]*)"
_NUM = _INT + r"(\.[0-9]+)?([eE][-+]?[0-9]+)?"
_BOOL = r"(true|false)"
_NULL = r"null"

# String `format`s lowered to regex fragments (the body between the
# quotes). These are the high-traffic tool-schema formats; unknown
# formats stay annotations (JSON-Schema's default vocabulary) and fall
# back to the free string grammar.
_TIME_BODY = (r"([01][0-9]|2[0-3]):[0-5][0-9]:[0-5][0-9](\.[0-9]+)?"
              r"(Z|[+\-]([01][0-9]|2[0-3]):[0-5][0-9])")
_DATE_BODY = r"[0-9]{4}-(0[1-9]|1[0-2])-(0[1-9]|[12][0-9]|3[01])"
_FORMAT_BODIES = {
    "date": _DATE_BODY,
    "date-time": _DATE_BODY + "T" + _TIME_BODY,
    "uuid": (r"[0-9a-fA-F]{8}-[0-9a-fA-F]{4}-[0-9a-fA-F]{4}-"
             r"[0-9a-fA-F]{4}-[0-9a-fA-F]{12}"),
    "email": (r"[A-Za-z0-9._%+\-]+@[A-Za-z0-9.\-]+\.[A-Za-z]{2,}"),
}


def _resolve_ref(root: dict, ref: str) -> dict:
    """Resolve a LOCAL JSON-pointer reference ('#/$defs/name',
    '#/definitions/name', or any '#/...' path) against the root
    schema. Remote/URL refs are refused loudly — this compiler has no
    retrieval layer, and silently treating them as free strings would
    change the constrained language."""
    if not isinstance(ref, str) or not ref.startswith("#"):
        raise ValueError(
            f"$ref {ref!r}: only local '#/...' references are supported"
        )
    node: Any = root
    for part in ref[1:].split("/"):
        if not part:
            continue
        part = part.replace("~1", "/").replace("~0", "~")
        if isinstance(node, dict) and part in node:
            node = node[part]
        elif isinstance(node, list) and part.isdigit() \
                and int(part) < len(node):
            node = node[int(part)]
        else:
            raise ValueError(f"$ref {ref!r}: path not found in schema")
    if not isinstance(node, dict):
        raise ValueError(f"$ref {ref!r}: target is not a schema object")
    return node


def _schema_regex(schema: dict, depth: int = 3, root: Optional[dict] = None,
                  seen: Tuple[str, ...] = ()) -> str:
    # `root` anchors $ref resolution ('#/...' points at the top-level
    # schema); `seen` is the ref chain of THIS path, so a reference
    # cycle (A -> B -> A) fails loudly instead of recursing forever —
    # a regex cannot express a recursive grammar.
    if root is None:
        root = schema
    if "$ref" in schema:
        ref = schema["$ref"]
        if ref in seen:
            raise ValueError(
                f"cyclic $ref chain {' -> '.join(seen + (ref,))}: a "
                "recursive schema cannot be regex-bounded"
            )
        return _schema_regex(
            _resolve_ref(root, ref), depth, root, seen + (ref,)
        )
    t = schema.get("type")
    for alt_key in ("anyOf", "oneOf"):
        if alt_key in schema:
            # Alternation. oneOf's exclusivity is NOT enforced (a
            # regex cannot count matches); it behaves as anyOf, the
            # public structured-output norm.
            subs = schema[alt_key]
            if not isinstance(subs, list) or not subs:
                raise ValueError(f"{alt_key} must be a non-empty list")
            return ("(" + "|".join(
                _schema_regex(s, depth, root, seen) for s in subs
            ) + ")")
    if "const" in schema:
        return _escape_literal(
            json.dumps(schema["const"], ensure_ascii=False,
                       separators=(",", ":"))
        )
    if "enum" in schema:
        alts = []
        for v in schema["enum"]:
            # ensure_ascii=False keeps non-Latin enum values as their
            # UTF-8 selves — the byte-level DFA constrains them
            # exactly (ASCII \\uXXXX escapes would force the model to
            # emit escape sequences instead of the actual characters).
            alts.append(_escape_literal(
                json.dumps(v, ensure_ascii=False, separators=(",", ":"))
            ))
        return "(" + "|".join(alts) + ")"
    if t == "string":
        if "pattern" in schema:
            # Group the user pattern: a top-level '|' must stay scoped
            # to the string body, not split the whole grammar.
            return '"(' + schema["pattern"] + ')"'
        fmt = schema.get("format")
        if fmt in _FORMAT_BODIES:
            return '"' + _FORMAT_BODIES[fmt] + '"'
        return _STR
    if t == "integer":
        return _INT
    if t == "number":
        return _NUM
    if t == "boolean":
        return _BOOL
    if t == "null":
        return _NULL
    if t == "array":
        if depth <= 0:
            raise ValueError("schema nests deeper than supported")
        item = _schema_regex(schema.get("items", {}), depth - 1, root,
                             seen)
        return r"\[(" + item + r"(," + item + r")*)?\]"
    if t == "object" or "properties" in schema:
        if depth <= 0:
            raise ValueError("schema nests deeper than supported")
        ap = schema.get("additionalProperties", False)
        extra_pair: Optional[str] = None
        if ap is not False and ap is not None:
            # Open object: undeclared pairs append AFTER the declared
            # (fixed-order) sequence. additionalProperties: true values
            # use the depth-limited generic-JSON grammar; a schema
            # constrains them like any declared property. The regex
            # cannot forbid an extra pair from re-using a declared
            # name — json.loads keeps the LAST occurrence (documented
            # in docs/structured_output.md).
            val = (_generic_json_regex(depth - 1, kind="value")
                   if ap is True
                   else _schema_regex(ap, depth - 1, root, seen))
            extra_pair = _STR + ":" + val
        props = schema.get("properties", {})
        if not props:
            # Free-form object: depth-limited generic JSON (with an
            # additionalProperties SCHEMA, its grammar types the
            # values).
            if extra_pair is not None and ap is not True:
                return (r"\{(" + extra_pair
                        + "(," + extra_pair + r")*)?\}")
            return _generic_json_regex(depth - 1, kind="object")
        required = schema.get("required")
        if required is None:
            # Back-compat with the fixed-order v1 compiler AND the
            # OpenAI structured-output norm: no `required` list means
            # every declared property is required. An explicit list
            # makes the others optional (JSON-Schema semantics).
            required = list(props.keys())
        req = set(required)
        unknown = req - set(props)
        if unknown:
            raise ValueError(
                f"required names {sorted(unknown)} not in properties"
            )
        parts = []
        for name, sub in props.items():
            key = _escape_literal(
                json.dumps(name, ensure_ascii=False)
            )
            parts.append((key + ":"
                          + _schema_regex(sub, depth - 1, root, seen),
                          name in req))
        # Fixed property order (the public structured-output norm for
        # regex-compiled schemas), compact separators; optional
        # properties may be absent, commas only between present ones.
        nonempty, can_empty = _prop_core(parts)
        if extra_pair is not None:
            tail = "(," + extra_pair + ")*"
            declared = "(" + nonempty + ")" + tail
            alone = extra_pair + tail
            if can_empty:
                return r"\{(" + declared + "|" + alone + r")?\}"
            return r"\{" + declared + r"\}"
        return (r"\{(" + nonempty + r")?\}" if can_empty
                else r"\{" + nonempty + r"\}")
    if t is None and not schema:
        return _generic_json_regex(depth - 1, kind="value")
    raise ValueError(f"unsupported schema fragment: {schema!r}")


def _prop_core(parts: List[Tuple[str, bool]]) -> Tuple[str, bool]:
    """Regex for fixed-order, comma-separated properties where
    optional ones may be absent: returns (regex of the NON-EMPTY
    realizations, may-the-whole-sequence-be-empty).

    Built right-to-left: for each suffix of the property list, compose
    (a) the regex of its non-empty realizations and (b) whether it may
    be empty. A required property anchors its suffix non-empty; an
    optional one alternates 'present (with correctly-placed comma)'
    against the rest."""
    nonempty: Optional[str] = None
    can_empty = True
    for body, required in reversed(parts):
        if nonempty is None:
            core = body
        elif can_empty:
            core = body + "(," + nonempty + ")?"
        else:
            core = body + "," + nonempty
        if required:
            nonempty = core
            can_empty = False
        else:
            nonempty = ("(" + core + "|" + nonempty + ")"
                        if nonempty is not None else core)
            # can_empty unchanged: this property may be skipped.
    assert nonempty is not None
    return nonempty, can_empty


def _escape_literal(s: str) -> str:
    return "".join(
        "\\" + c if c in r"\.[]{}()*+?|^$" else c for c in s
    )


def _generic_json_regex(depth: int, kind: str = "value") -> str:
    """Depth-limited generic JSON value (regular approximation of the
    recursive grammar; depth levels of nesting)."""
    scalar = f"({_STR}|{_NUM}|{_BOOL}|{_NULL})"
    value = scalar
    for _ in range(max(depth, 0)):
        obj = r"\{(" + _STR + ":" + value + "(," + _STR + ":" + value + r")*)?\}"
        arr = r"\[(" + value + "(," + value + r")*)?\]"
        value = f"({scalar}|{obj}|{arr})"
    if kind == "object":
        return r"\{(" + _STR + ":" + value + "(," + _STR + ":" + value + r")*)?\}"
    return value


def constraint_pattern(spec: dict) -> str:
    """Normalize a user constraint spec into one regex pattern.

    spec: {"regex": ...} | {"json_schema": {...}} | {"json_object": true}
    (the native API shape; the OpenAI response_format translates onto
    this in the server).
    """
    if not isinstance(spec, dict):
        raise ValueError("constraint must be an object")
    keys = [k for k in ("regex", "json_schema", "json_object") if k in spec]
    if len(keys) != 1:
        raise ValueError(
            "constraint needs exactly one of regex/json_schema/json_object"
        )
    if keys[0] == "regex":
        if not isinstance(spec["regex"], str):
            raise ValueError("constraint.regex must be a string")
        return spec["regex"]
    if keys[0] == "json_schema":
        return _schema_regex(spec["json_schema"])
    return _generic_json_regex(2, kind="object")
