"""SLO-actuated autoscaler for the serving tier.

The tier already owns every signal an autoscaler needs — the SLO
burn-rate engine pages on fast error budget burn, the health sweep
scrapes a per-replica load score, and `replica_factory` can mint
capacity on demand (PR 8 wired it for crash respawn). What is missing
is the POLICY that closes the loop: when a page lands, add a replica;
when the fleet sits idle, drain one. This module is that policy and
nothing else — it holds no sockets, spawns no threads, and reads no
clocks it was not handed, so tests drive it tick-by-tick with a fake
clock and fake actuators.

Design rules (each one is a production scar):

  hysteresis — load must stay above/below its threshold for
    `hysteresis` CONSECUTIVE ticks before it counts. A single noisy
    scrape (one replica answering /metrics late) must not buy a TPU.

  cooldown — after any action, no further action for `cooldown_s`.
    A scale-out takes time to absorb load (the new replica's cache is
    cold); acting again before the last action's effect is visible
    oscillates: out, still paging, out, out, recovered, drain, drain.

  envelope — `min_replicas` and `max_replicas` bound the fleet
    absolutely. A paging SLO at max does NOT scale out (the page keeps
    firing — that is the operator's signal that the envelope is the
    bottleneck); idle at min does not drain.

  evidence — every decision (including refusals: at-max, in-cooldown)
    is a flight-recorder event, and every ACTION additionally bumps
    `shellac_autoscale_actions_total` and fires an incident trigger —
    capacity changes are exactly the moments an incident review wants
    the whole evidence surface frozen.

The tier calls `on_slo_transition` from its SLOEngine hook and
`tick()` from the health-poll cadence. `--no-autoscale` (the default)
constructs nothing, so a tier without the flag is bit-identical to one
predating this module.
"""
from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional


@dataclass(frozen=True)
class AutoscalePolicy:
    """The operator-tunable envelope. Validated eagerly: a bad flag
    must fail `serve-tier` startup, not the first page at 3am."""

    min_replicas: int = 1
    max_replicas: int = 4
    cooldown_s: float = 60.0
    # Sustained-idle drain: per-routable-replica load must stay at or
    # under `idle_load` for `idle_after_s` continuous seconds.
    idle_after_s: float = 300.0
    idle_load: float = 0.5
    # Load-pressure scale-out (the per-tenant gauges feed the tier's
    # score): per-routable load must exceed `high_load` for
    # `hysteresis` consecutive ticks. Pages bypass hysteresis — the
    # burn-rate engine already smoothed them.
    high_load: float = 16.0
    hysteresis: int = 3

    def __post_init__(self) -> None:
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if self.idle_after_s <= 0:
            raise ValueError("idle_after_s must be > 0")
        if self.hysteresis < 1:
            raise ValueError("hysteresis must be >= 1")
        if self.high_load <= self.idle_load:
            raise ValueError("high_load must exceed idle_load "
                             "(the hysteresis band would be empty)")


class Autoscaler:
    """Policy engine: consumes SLO transitions + load observations,
    emits at most one scale action per tick through injected
    actuators.

    `scale_out()` must add one replica and return its URL (or None if
    the attempt failed — counted, retried next tick after cooldown).
    `scale_down()` must pick and drain one replica and return its URL
    (or None). `observe()` returns (routable_replicas, total_replicas,
    aggregate_load_score) — the tier sums its per-replica scores.

    NOT thread-safe by design: the tier calls every method from its
    poller thread (`on_slo_transition` fires inside `slo.tick()`,
    which the poller runs). Single-writer means no lock and no
    lock-ordering story with the router's own locks.
    """

    def __init__(
        self,
        policy: AutoscalePolicy,
        *,
        scale_out: Callable[[], Optional[str]],
        scale_down: Callable[[], Optional[str]],
        observe: Callable[[], Any],
        on_action: Optional[Callable[..., None]] = None,
        now: Callable[[], float] = time.monotonic,
    ) -> None:
        self.policy = policy
        self._scale_out = scale_out
        self._scale_down = scale_down
        self._observe = observe
        self._on_action = on_action
        self._now = now
        t = now()
        # Start IN cooldown: a tier that boots under load should let
        # the fleet it was configured with serve for one cooldown
        # before concluding it is undersized.
        self._last_action_t: float = t
        self._last_action: Optional[str] = None
        self._last_url: Optional[str] = None
        self._page_pending: Optional[str] = None  # paging SLO name
        self._idle_since: Optional[float] = None
        self._hot_ticks: int = 0
        self._actions: int = 0
        self._failures: int = 0

    # ---- inputs ------------------------------------------------------

    def on_slo_transition(self, name: str, old: str, new: str) -> None:
        """SLOEngine hook. A page arms a scale-out (consumed by the
        next tick outside cooldown); a recovery to ok disarms it —
        paging five minutes ago is not a reason to buy capacity that
        the budget burn already stopped needing."""
        del old
        if new == "page":
            self._page_pending = name
        elif new == "ok" and self._page_pending == name:
            self._page_pending = None

    # ---- the loop ----------------------------------------------------

    def tick(self) -> Optional[str]:
        """One policy evaluation. Returns the action taken
        ("scale_out" | "scale_down") or None. At most one action per
        tick; all the guard state (hysteresis, idle timer) still
        advances on ticks that act or refuse."""
        now = self._now()
        routable, total, load = self._observe()
        per = load / max(routable, 1)

        # Advance the continuous-signal trackers every tick, even in
        # cooldown — a cooldown must delay the ACTION, not reset the
        # evidence that one is needed.
        if per > self.policy.high_load:
            self._hot_ticks += 1
        else:
            self._hot_ticks = 0
        if per <= self.policy.idle_load and routable > 0:
            if self._idle_since is None:
                self._idle_since = now
        else:
            self._idle_since = None

        if now - self._last_action_t < self.policy.cooldown_s:
            return None

        want_out = (self._page_pending is not None
                    or self._hot_ticks >= self.policy.hysteresis)
        if want_out:
            if total >= self.policy.max_replicas:
                self._emit("refused_at_max", None,
                           reason=self._reason(), replicas=total)
                # Consume the page: re-paging re-arms. Otherwise a
                # fleet pinned at max re-logs the refusal every tick
                # forever.
                self._page_pending = None
                self._hot_ticks = 0
                return None
            return self._act("scale_out", self._scale_out,
                             now, total)

        idle_for = (now - self._idle_since
                    if self._idle_since is not None else 0.0)
        if (self._idle_since is not None
                and idle_for >= self.policy.idle_after_s
                and routable > self.policy.min_replicas):
            return self._act("scale_down", self._scale_down,
                             now, total)
        return None

    def _act(self, action: str, fn: Callable[[], Optional[str]],
             now: float, total: int) -> Optional[str]:
        reason = self._reason() if action == "scale_out" else "idle"
        url = None
        try:
            url = fn()
        except Exception:  # noqa: BLE001 — an actuator fault (factory
            # raised, drain POST refused) must not kill the poller;
            # counted and retried after the cooldown.
            url = None
        if url is None:
            self._failures += 1
            self._emit(f"{action}_failed", None, reason=reason,
                       replicas=total)
            # Failed actions still start the cooldown: a broken
            # factory hammered every tick is a respawn storm.
            self._last_action_t = now
            return None
        self._actions += 1
        self._last_action_t = now
        self._last_action = action
        self._last_url = url
        if action == "scale_out":
            self._page_pending = None
            self._hot_ticks = 0
        else:
            self._idle_since = None
        self._emit(action, url, reason=reason, replicas=total)
        return action

    def _reason(self) -> str:
        if self._page_pending is not None:
            return f"slo-page:{self._page_pending}"
        return "load"

    def _emit(self, action: str, url: Optional[str],
              **detail: Any) -> None:
        if self._on_action is not None:
            try:
                self._on_action(action, url, **detail)
            except Exception:  # noqa: BLE001 — evidence emission is
                pass           # best-effort; the decision already ran

    # ---- introspection ----------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The /stats + `top` payload. Pure reads, poller-thread
        values — possibly one tick stale, never torn."""
        now = self._now()
        cooldown_left = max(
            0.0, self.policy.cooldown_s - (now - self._last_action_t)
        )
        return {
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "cooldown_s": self.policy.cooldown_s,
            "cooldown_remaining_s": round(cooldown_left, 3),
            "last_action": self._last_action,
            "last_action_replica": self._last_url,
            "page_pending": self._page_pending,
            "hot_ticks": self._hot_ticks,
            "idle_for_s": (round(now - self._idle_since, 3)
                           if self._idle_since is not None else 0.0),
            "actions": self._actions,
            "failures": self._failures,
        }
