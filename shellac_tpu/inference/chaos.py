"""Serving-tier chaos harness: deterministic fault injection at the
wire and process level.

The training twin (`training/chaos.py`) injects faults into the data
stream and the checkpoint directory; this one injects them between the
router and its replicas, and into the replica processes themselves —
the failure classes a multi-replica tier actually meets:

  - `ChaosProxy`: a byte-level TCP proxy slotted between the router
    and one replica, with switchable modes — `pass_through`, `refuse`
    (connection reset: replica process gone), `unavailable` (canned
    503 + Retry-After: replica recovering), `stall` (accept, read,
    never answer: wedged replica), `cut_stream(n)` (forward the
    response but sever it after n bytes: replica killed mid-stream).
    Because the proxy sits on the wire, what the chaos tests prove is
    the ROUTER's public failure contract — ejection, retry, loud
    mid-stream failure — not anything about replica internals.
  - `ReplicaProc`: a real `python -m shellac_tpu serve` subprocess
    (the CLI path operators run), so a SIGKILL is a true process
    death: sockets reset, no goodbye, exactly what a preempted node
    looks like to the tier.
  - `LoadGenerator`: sustained traffic with per-request deadlines,
    counting outcomes — the background load the acceptance scenarios
    (kill under load, drain under load) assert "zero failures"
    against. Two drive modes: the original CLOSED loop (`concurrency`
    workers back-to-back — throughput-coupled, the server slowing
    down slows the offered load) and an OPEN loop (`schedule=` or
    `rate=` — arrival-driven, the production shape where traffic does
    not care that the server is slow; `run()` plays a deterministic
    (arrival_s, payload) schedule, e.g. from
    `workload.WorkloadModel.payload_schedule()`). Payloads may carry
    reserved client-side keys — `tenant` (sent as the
    x-shellac-tenant header, never in the body), `kind` (a label for
    the tally), `stream` + `cancel_after_deltas` (read the NDJSON
    stream and optionally sever it mid-flight: the client-cancel
    path) — and the tally splits per tenant; with `capture=True`
    every request also leaves a result row (latency, TTFT, outcome,
    trace id) the scenario gate computes SLIs from. `seed=` makes
    closed-loop payload draws deterministic. The shape helpers
    (`zipf_tenant_mix`, `abusive_burst_mix`, `interactive_batch_mix`)
    build multi-tenant payload lists with the traffic skews real
    fleets meet: Zipf tenant popularity, one abusive tenant at N×
    everyone else, and an interactive-vs-batch class split.

Injectors never reach into `TierRouter` or `InferenceServer`
internals; docs/serving_tier.md documents the contract they exercise.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time
import random
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from shellac_tpu.inference.qos import TENANT_HEADER


class ChaosProxy:
    """TCP proxy with switchable failure modes between a client (the
    tier router) and one upstream replica.

    Mode changes apply to NEW connections; `cut_stream` additionally
    severs the connection that crosses the byte budget mid-flight.
    Thread-safe; `url` is what you hand the router as the replica
    address."""

    PASS = "pass"
    REFUSE = "refuse"
    UNAVAILABLE = "unavailable"
    STALL = "stall"
    CUT = "cut"

    def __init__(self, upstream_host: str, upstream_port: int,
                 host: str = "127.0.0.1"):
        self.upstream = (upstream_host, int(upstream_port))
        self._mode = self.PASS
        self._cut_after = 0
        self._retry_after = 1
        self._lock = threading.Lock()
        self._stall_release = threading.Event()
        proxy = self

        class _Conn(socketserver.BaseRequestHandler):
            def handle(self):
                proxy._handle(self.request)

        class _Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = _Server((host, 0), _Conn)
        self.port = self._server.server_address[1]
        self.url = f"http://{host}:{self.port}"
        threading.Thread(target=self._server.serve_forever,
                         daemon=True).start()

    # ---- mode switches ----------------------------------------------

    def pass_through(self):
        with self._lock:
            self._mode = self.PASS

    def refuse(self):
        """New connections are reset immediately — the wire shape of a
        dead process / closed port."""
        with self._lock:
            self._mode = self.REFUSE

    def unavailable(self, retry_after: int = 1):
        """Answer every request with a canned 503 + Retry-After — the
        wire shape of a replica mid-recovery."""
        with self._lock:
            self._mode = self.UNAVAILABLE
            self._retry_after = retry_after

    def stall(self):
        """Accept and read, never answer — the wire shape of a wedged
        replica. `release_stalls()` unblocks held connections (tests
        must release before teardown so no handler thread leaks)."""
        with self._lock:
            self._mode = self.STALL
            self._stall_release.clear()

    def cut_stream(self, after_bytes: int):
        """Forward the response but sever the connection once
        `after_bytes` response bytes have crossed — a replica killed
        mid-stream, after the client already saw tokens."""
        with self._lock:
            self._mode = self.CUT
            self._cut_after = int(after_bytes)

    def release_stalls(self):
        self._stall_release.set()

    def close(self):
        self._stall_release.set()
        self._server.shutdown()
        self._server.server_close()

    # ---- the wire ----------------------------------------------------

    def _handle(self, client: socket.socket) -> None:
        with self._lock:
            mode = self._mode
            cut_after = self._cut_after
        try:
            if mode == self.REFUSE:
                # RST instead of FIN: a crash, not a polite close.
                client.setsockopt(
                    socket.SOL_SOCKET, socket.SO_LINGER,
                    b"\x01\x00\x00\x00\x00\x00\x00\x00",
                )
                client.close()
                return
            if mode == self.UNAVAILABLE:
                client.settimeout(5.0)
                try:
                    client.recv(65536)  # drain the request politely
                except OSError:
                    pass
                body = json.dumps(
                    {"error": "chaos: replica unavailable"}
                ).encode()
                client.sendall(
                    b"HTTP/1.0 503 Service Unavailable\r\n"
                    b"Content-Type: application/json\r\n"
                    + f"Retry-After: {self._retry_after}\r\n".encode()
                    + f"Content-Length: {len(body)}\r\n\r\n".encode()
                    + body
                )
                client.close()
                return
            if mode == self.STALL:
                client.settimeout(1.0)
                try:
                    client.recv(65536)
                except OSError:
                    pass
                # Hold the connection open, answering nothing, until
                # released or the far side gives up.
                self._stall_release.wait(120)
                client.close()
                return
            # PASS / CUT: full duplex byte pump.
            up = socket.create_connection(self.upstream, timeout=10)
            budget = cut_after if mode == self.CUT else None
            t = threading.Thread(
                target=self._pump, args=(client, up, None), daemon=True
            )
            t.start()
            self._pump(up, client, budget)
            t.join(timeout=10)
        except OSError:
            pass
        finally:
            try:
                client.close()
            except OSError:
                pass

    @staticmethod
    def _pump(src: socket.socket, dst: socket.socket,
              budget: Optional[int]) -> None:
        """Copy src -> dst until EOF; with a byte budget, sever BOTH
        sockets once it is spent (response direction only)."""
        sent = 0
        try:
            while True:
                data = src.recv(4096)
                if not data:
                    break
                if budget is not None and sent + len(data) > budget:
                    dst.sendall(data[: max(0, budget - sent)])
                    raise ConnectionAbortedError("chaos cut")
                dst.sendall(data)
                sent += len(data)
        except OSError:
            pass
        finally:
            for s in (src, dst):
                try:
                    s.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass


class ReplicaProc:
    """One real replica: `python -m shellac_tpu serve` as a subprocess.

    Binds port 0 and reports the actual address from the CLI's
    `{"serving": ...}` startup line, so parallel replicas never
    collide. `kill()` is SIGKILL — no drain, no goodbye — and
    `drain()` posts the graceful path for contrast."""

    def __init__(self, *, model: str = "tiny",
                 config_path: Optional[str] = None, seed: int = 0,
                 slots: int = 2, max_len: int = 96,
                 extra_args: Optional[List[str]] = None,
                 startup_timeout: float = 120.0):
        # decode_ticks pinned to 1: chaos replicas measure failure
        # semantics, not throughput, and the serve default ("auto")
        # would spend replica startup on a tuning sweep. Overlapped
        # dispatch keeps its serve default, so the chaos scenarios
        # exercise SIGKILL/drain against the overlapped pipeline.
        # extra_args may override either (argparse: last flag wins).
        cmd = [sys.executable, "-m", "shellac_tpu", "serve",
               "--port", "0", "--slots", str(slots),
               "--max-len", str(max_len), "--seed", str(seed),
               "--decode-ticks", "1",
               "--temperature", "0.0", "--tokenizer", "byte"]
        cmd += (["--config", config_path] if config_path
                else ["--model", model])
        cmd += list(extra_args or ())
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True,
        )
        self.url: Optional[str] = None
        # Read stdout on a side thread: a subprocess that wedges
        # during startup and prints NOTHING must hit startup_timeout,
        # not park this constructor in a blocking readline forever.
        lines: "queue.Queue[str]" = queue.Queue()
        stdout = self.proc.stdout

        def _reader():
            for ln in stdout:
                lines.put(ln)

        threading.Thread(target=_reader, daemon=True).start()
        deadline = time.monotonic() + startup_timeout
        line = ""
        while time.monotonic() < deadline:
            try:
                line = lines.get(timeout=0.5)
            except queue.Empty:
                if self.proc.poll() is not None:
                    raise RuntimeError(
                        f"replica exited rc={self.proc.returncode} "
                        "before serving"
                    )
                continue
            try:
                self.url = json.loads(line)["serving"]
                break
            except (ValueError, KeyError):
                continue
        if self.url is None:
            self.kill()
            raise TimeoutError(
                f"replica never reported serving (last line {line!r})"
            )

    def wait_ready(self, timeout: float = 120.0) -> None:
        """Block until /health answers 200 (first request may compile)."""
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                with urllib.request.urlopen(
                        self.url + "/health", timeout=5) as r:
                    if r.status == 200:
                        return
            except (OSError, urllib.error.URLError):
                pass
            time.sleep(0.2)
        raise TimeoutError(f"replica {self.url} never became ready")

    def drain(self, resume: bool = False) -> dict:
        req = urllib.request.Request(
            self.url + "/drain",
            data=json.dumps({"resume": resume} if resume else {}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=10) as r:
            return json.loads(r.read())

    def kill(self) -> None:
        """SIGKILL: the unplanned death. Idempotent."""
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=30)
        if self.proc.stdout:
            self.proc.stdout.close()

    def terminate(self) -> None:
        if self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.kill()
                return
        if self.proc.stdout:
            self.proc.stdout.close()


class LoadGenerator:
    """Background load through the tier, in two drive modes.

    CLOSED (the default, the original behavior): `concurrency`
    threads each issue POSTs back-to-back until stopped — offered
    load couples to server throughput, which is what the chaos
    acceptance scenarios want ("zero failures while a replica was
    killed"). `seed=` makes each worker draw its payload sequence
    from a seeded rng instead of cycling by index, so a multi-shape
    closed run is reproducible.

    OPEN (`schedule=` a sorted [(arrival_s, payload), ...] list, or
    `rate=` + `duration=` for seeded Poisson arrivals over
    `payloads`): a dispatcher fires each request at its arrival
    offset regardless of how the server is doing — the production
    shape an SLO gate must measure under, because a slow server and
    open-loop arrivals is exactly how queues actually build. Arrivals
    never block on in-flight work; past `max_in_flight` the request
    is counted `client_saturated` (the load generator ran out of
    client capacity — loud, never silently re-timed). `run()` plays
    the whole schedule and returns the tally.

    Payloads may carry reserved client-side keys: `tenant` (the
    x-shellac-tenant header), `kind` (tally label only), `stream`
    (read the NDJSON stream; `stream` DOES go to the wire) and
    `cancel_after_deltas` (sever the stream after N delta lines — the
    client-cancel path; tallied `cancelled`). A stream that ends
    without its `{"done": ...}` line is `stream_severed`. With
    `capture=True` each request appends a result row to `.results`:
    arrival/latency/TTFT seconds, outcome, tenant, kind, and the
    trace id from the response's x-request-id header — the raw
    material the scenario gate computes SLIs and violating-trace
    exemplars from."""

    def __init__(self, base_url: str, *, path: str = "/generate",
                 payloads: Optional[List[dict]] = None,
                 concurrency: int = 4, timeout: float = 30.0,
                 schedule: Optional[List] = None,
                 rate: Optional[float] = None,
                 duration: Optional[float] = None,
                 seed: Optional[int] = None,
                 max_in_flight: int = 64,
                 capture: bool = False):
        self.base_url = base_url.rstrip("/")
        self.path = path
        # One payload per worker (cycled): distinct prompts give the
        # workers distinct affinity keys, so load spreads across the
        # tier instead of piling onto one replica's session.
        self.payloads = payloads or [
            {"tokens": [1 + i, 2 + i, 3 + i], "max_new": 4}
            for i in range(max(1, concurrency))
        ]
        self.concurrency = concurrency
        self.timeout = timeout
        self.seed = seed
        self.capture = bool(capture)
        self.max_in_flight = int(max_in_flight)
        if rate is not None and rate <= 0:
            raise ValueError(f"rate must be > 0 (got {rate})")
        if rate is not None and duration is None and schedule is None:
            raise ValueError("open-loop rate= needs duration=")
        if schedule is not None:
            self.schedule = [(float(t), dict(p)) for t, p in schedule]
            self.schedule.sort(key=lambda tp: tp[0])
        elif rate is not None:
            # Seeded Poisson arrivals over the payload list, cycled.
            rng = random.Random(seed if seed is not None else 0)
            self.schedule = []
            t, i = 0.0, 0
            while True:
                t += rng.expovariate(rate)
                if t >= duration:
                    break
                self.schedule.append(
                    (t, dict(self.payloads[i % len(self.payloads)])))
                i += 1
        else:
            self.schedule = None  # closed loop
        self.counts: Dict[str, int] = {}
        # Per-tenant outcome split (only for payloads that carried a
        # `tenant` key): {tenant: {outcome: count}}.
        self.by_tenant: Dict[str, Dict[str, int]] = {}
        self.errors: List[str] = []
        self.results: List[dict] = []
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._in_flight = threading.Semaphore(self.max_in_flight)

    def _tally(self, key: str, detail: str = "",
               tenant: Optional[str] = None) -> None:
        with self._lock:
            self.counts[key] = self.counts.get(key, 0) + 1
            if tenant is not None:
                per = self.by_tenant.setdefault(tenant, {})
                per[key] = per.get(key, 0) + 1
            if detail and len(self.errors) < 50:
                self.errors.append(detail)

    def _record(self, row: dict) -> None:
        if not self.capture:
            return
        with self._lock:
            self.results.append(row)

    def _one(self, payload: dict, arrival_s: Optional[float] = None
             ) -> None:
        """Issue one request (streaming or not), tally the outcome,
        and capture a result row. `payload` still carries its
        reserved keys; they are stripped here."""
        p = dict(payload)
        tenant = p.pop("tenant", None)
        kind = p.pop("kind", None)
        cancel_after = p.pop("cancel_after_deltas", None)
        stream = bool(p.get("stream"))
        p.setdefault("timeout", self.timeout)
        body = json.dumps(p).encode()
        headers = {"Content-Type": "application/json"}
        if tenant is not None:
            headers[TENANT_HEADER] = tenant
        req = urllib.request.Request(
            self.base_url + self.path, data=body, headers=headers,
        )
        t0 = time.monotonic()
        row = {"arrival_s": arrival_s, "tenant": tenant, "kind": kind,
               "stream": stream, "trace_id": None, "ttft_s": None,
               "latency_s": None, "status": None, "outcome": None}

        def settle(outcome: str, detail: str = "") -> None:
            row["latency_s"] = time.monotonic() - t0
            row["outcome"] = outcome
            self._tally(outcome, detail, tenant=tenant)
            self._record(row)

        try:
            # Read timeout sits above the request deadline so the TIER
            # classifies a blown deadline (504), not the client socket.
            with urllib.request.urlopen(req,
                                        timeout=self.timeout + 15) as r:
                row["status"] = r.status
                row["trace_id"] = r.headers.get("x-request-id")
                if not stream:
                    r.read()
                    settle("ok" if r.status == 200
                           else f"http_{r.status}")
                    return
                # NDJSON stream: each line is a delta until the
                # {"done": ...} record. TTFT = first delta line.
                deltas = 0
                done = False
                for raw in r:
                    if not raw.strip():
                        continue
                    try:
                        obj = json.loads(raw)
                    except ValueError:
                        settle("stream_garbled", raw[:120].decode(
                            errors="replace"))
                        return
                    if obj.get("error"):
                        settle("stream_error", str(obj)[:200])
                        return
                    if obj.get("done"):
                        done = True
                        break
                    deltas += 1
                    if row["ttft_s"] is None:
                        row["ttft_s"] = time.monotonic() - t0
                    if (cancel_after is not None
                            and deltas >= cancel_after):
                        # Client cancel: just stop reading and close
                        # the socket (the `with` does) — the server
                        # sees the hangup and settles `cancelled`.
                        settle("cancelled")
                        return
                settle("ok" if done else "stream_severed")
        except urllib.error.HTTPError as e:
            row["status"] = e.code
            row["trace_id"] = e.headers.get("x-request-id")
            detail = ""
            try:
                detail = e.read().decode(errors="replace")[:200]
            except OSError:
                pass
            settle(f"http_{e.code}", f"{e.code}: {detail}")
        except (OSError, urllib.error.URLError) as e:
            settle("connect_error", repr(e))

    # ---- closed loop -------------------------------------------------

    def _loop(self, idx: int) -> None:
        rng = (random.Random(f"{self.seed}:{idx}")
               if self.seed is not None else None)
        while not self._stop.is_set():
            if rng is not None:
                payload = rng.choice(self.payloads)
            else:
                payload = self.payloads[idx % len(self.payloads)]
            self._one(payload)

    def start(self) -> "LoadGenerator":
        if self.schedule is not None:
            t = threading.Thread(target=self._dispatch, daemon=True)
            t.start()
            self._threads.append(t)
            return self
        for i in range(self.concurrency):
            t = threading.Thread(target=self._loop, args=(i,), daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def stop(self) -> Dict[str, int]:
        """Signal stop, join every worker (each finishes its in-flight
        request), and return the final tally."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=self.timeout + 30)
        with self._lock:
            return dict(self.counts)

    # ---- open loop ---------------------------------------------------

    def _dispatch(self) -> None:
        """Play the schedule: sleep to each arrival offset, fire the
        request on its own thread. Firing never waits on in-flight
        work — that is the open-loop contract."""
        fired: List[threading.Thread] = []
        t0 = time.monotonic()
        for arrival_s, payload in self.schedule:
            if self._stop.is_set():
                break
            delay = t0 + arrival_s - time.monotonic()
            if delay > 0 and self._stop.wait(delay):
                break
            if not self._in_flight.acquire(blocking=False):
                self._tally("client_saturated",
                            tenant=payload.get("tenant"))
                self._record({
                    "arrival_s": arrival_s,
                    "tenant": payload.get("tenant"),
                    "kind": payload.get("kind"),
                    "stream": bool(payload.get("stream")),
                    "trace_id": None, "ttft_s": None,
                    "latency_s": None, "status": None,
                    "outcome": "client_saturated",
                })
                continue

            def fire(p=payload, a=arrival_s):
                try:
                    self._one(p, arrival_s=a)
                finally:
                    self._in_flight.release()

            th = threading.Thread(target=fire, daemon=True)
            th.start()
            fired.append(th)
        for th in fired:
            th.join(timeout=self.timeout + 30)

    def run(self) -> Dict[str, int]:
        """Open-loop only: play the whole schedule to completion
        (blocking) and return the tally."""
        if self.schedule is None:
            raise RuntimeError(
                "run() needs an open-loop schedule (schedule= or "
                "rate=+duration=); use start()/stop() for closed loop"
            )
        self.start()
        for t in self._threads:
            t.join()
        with self._lock:
            return dict(self.counts)

    @property
    def total(self) -> int:
        with self._lock:
            return sum(self.counts.values())


# ---- multi-tenant traffic shapes ------------------------------------
# Payload-list builders for LoadGenerator(payloads=...): each entry is
# one worker's steady request, with the reserved `tenant` key naming
# who it bills to. Deterministic (seeded) so a chaos run's tenant mix
# is reproducible run-to-run.

def zipf_tenant_mix(tenants: List[str], concurrency: int,
                    s: float = 1.2, seed: int = 7,
                    max_new: int = 4) -> List[dict]:
    """Zipf tenant popularity: worker i's tenant is drawn with
    P(rank r) ∝ 1/r^s over `tenants` (list order = popularity rank) —
    the heavy-head/long-tail skew real multi-tenant fleets see, where
    one tenant dominates and most barely show up."""
    if not tenants:
        raise ValueError("zipf_tenant_mix needs at least one tenant")
    rng = random.Random(seed)
    weights = [1.0 / (r + 1) ** s for r in range(len(tenants))]
    out = []
    for i in range(max(1, concurrency)):
        t = rng.choices(tenants, weights=weights)[0]
        out.append({"tokens": [1 + i, 2 + i, 3 + i],
                    "max_new": max_new, "tenant": t})
    return out


def abusive_burst_mix(victim: str, abuser: str, concurrency: int,
                      abuse_ratio: int = 10,
                      max_new: int = 4) -> List[dict]:
    """One well-behaved tenant vs one abusive tenant flooding at
    ~abuse_ratio× its worker share — the starvation scenario: the
    assertion is that `victim`'s tally stays clean (zero rejections,
    p99 within SLO) while `abuser` eats 429s."""
    if concurrency < abuse_ratio + 1:
        concurrency = abuse_ratio + 1
    out = []
    for i in range(concurrency):
        t = victim if i % (abuse_ratio + 1) == 0 else abuser
        out.append({"tokens": [1 + i, 2 + i, 3 + i],
                    "max_new": max_new, "tenant": t})
    return out


def interactive_batch_mix(interactive: str, batch: str,
                          concurrency: int,
                          batch_max_new: int = 32) -> List[dict]:
    """Interactive-vs-batch class split: short interactive requests
    interleaved with long-decode batch requests — the mix where
    weighted-fair scheduling and preempt-and-park earn their keep
    (without them, one batch tenant's long decodes monopolize the
    slots and interactive TTFT collapses)."""
    out = []
    for i in range(max(2, concurrency)):
        if i % 2 == 0:
            out.append({"tokens": [1 + i, 2 + i, 3 + i],
                        "max_new": 2, "tenant": interactive})
        else:
            out.append({"tokens": [1 + i, 2 + i, 3 + i, 4 + i,
                                   5 + i, 6 + i],
                        "max_new": batch_max_new, "tenant": batch})
    return out
