"""Disaggregated prefill/decode serving: the KV-migration subsystem.

Prefill is compute-bound and bursty; decode is memory-bandwidth-bound
and steady. Co-locating them makes every replica bad at both (the
shellac_step_phase_seconds{phase="prefill_dispatch"} share is the
committed measurement of the interference). This module is the seam
that splits them: a PREFILL replica runs the prompt, freezes the slot
(the engine's device-side done flag — PR 7's freeze mechanism), and
ships the slot's KV state to a DECODE replica, which re-registers the
blocks with its own allocator and streams tokens as if it had
prefilled locally.

The migration contract is built on `CacheBackend.residency()` being
JSON-serializable per-slot state and on the paged backend owning ALL
allocator state host-side: "migrate a request" is exactly "transfer
its blocks and re-register them" (`ensure_blocks` grows the importer's
table; the device only ever sees tables, so block ids are free to
differ across replicas).

Wire format (version 1, `MigrationBlob.serialize`):

    magic "SHLKV1\\0" | u32 header length | JSON header | raw payload

The header carries the backend registry name, per-array dtype/shape,
the backend's `residency()` manifest, the full request state (prompt,
sampling settings, the prefill-sampled token(s), logprob sidecars),
the engine agreement block (eos_id, logprobs, top_logprobs), the model
geometry fingerprint, and the trace id (PR 10) — so one id walks the
prefill replica's recorder, the transfer, and the decode replica's
recorder. The device payload is CHUNKED: each array is split into
`chunk_bytes` chunks, each with its own crc32, so a truncated or
corrupted transfer is refused loudly at deserialize instead of
decoding garbage KV. Chunk size is a knob on purpose: the transfer
path is characterized (bytes histogram + seconds histogram), not
guessed — the CUDA-aware-MPI discipline from PAPERS.md.

Token identity across the migration (tested in tests/test_disagg.py
and the test_cache_backends.py conformance suite): greedy requests
are bit-identical because the decode math reads the same KV values at
the same positions; seeded requests are identical because sampling
derives from the REQUEST's (seed, gen_idx) stream, not the engine's
shared key. Unseeded sampled requests draw from the destination
engine's stream — the same caveat as any scheduling change.

Out of scope (loud refusals, never silent): cross-backend migration
(the wire format names the backend and the importer must match),
constrained requests (a compiled TokenDFA does not serialize),
speculative engines (the draft cache is unshipped state), and
patterned local/global rolling caches.
"""

from __future__ import annotations

import json
import zlib
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu.inference.cache import PoolExhausted
from shellac_tpu.inference.kvcache import kv_field_names

MAGIC = b"SHLKV1\x00"
VERSION = 1
#: Default transfer chunk size. Each chunk carries its own crc32 in the
#: header, so integrity granularity (and any future streaming overlap
#: of transfer with compute) is tunable without a format bump.
DEFAULT_CHUNK_BYTES = 1 << 20

#: Backends the migration path supports — exactly the registry.
SUPPORTED_BACKENDS = ("dense", "dense-int8", "paged", "paged-int8",
                      "rolling", "rolling-int8")


def _np_dtype(name: str) -> np.dtype:
    """np.dtype by name, including the ml_dtypes extensions jax caches
    use (bfloat16)."""
    try:
        return np.dtype(name)
    except TypeError:
        if name == "bfloat16":
            return np.dtype(jnp.bfloat16)
        raise ValueError(f"unknown array dtype {name!r} in KV blob")


def model_fingerprint(engine) -> Dict[str, Any]:
    """The geometry both sides must agree on for imported KV to mean
    the same thing to the importer's decode programs. `dtype` is the
    cache compute dtype: without it a bf16->f32 pair would silently
    CAST the KV at import (jnp .set casts) instead of refusing — the
    one mismatch the array shapes cannot catch."""
    cfg = engine.cfg
    return {
        "n_layers": int(cfg.n_layers),
        "kv_heads": int(cfg.cache_kv_heads),
        "head_dim": int(cfg.cache_head_dim),
        "v_head_dim": int(cfg.cache_v_head_dim),
        "vocab_size": int(cfg.vocab_size),
        "dtype": str(jnp.dtype(cfg.compute_dtype).name),
    }


def _engine_agreement(engine) -> Dict[str, Any]:
    """Engine-level settings that change the decode MATH or the render
    surface: a mismatch would silently break token identity (eos) or
    drop sidecars a client asked for (logprobs)."""
    return {
        "eos_id": engine.eos_id,
        "logprobs": bool(engine.logprobs),
        "top_logprobs": int(engine.top_logprobs),
    }


class MigrationBlob:
    """One migratable request: JSON header + named device arrays."""

    __slots__ = ("header", "arrays")

    def __init__(self, header: Dict[str, Any],
                 arrays: Dict[str, np.ndarray]):
        self.header = header
        self.arrays = arrays

    # ---- wire format -------------------------------------------------

    def serialize(self, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> bytes:
        """MAGIC | u32 header-len | header JSON | concatenated array
        bytes. The header's `arrays` manifest records, per array:
        name, dtype, shape, and the per-chunk crc32 list (chunks of
        `chunk_bytes`, last one short)."""
        if chunk_bytes < 1:
            raise ValueError("chunk_bytes must be >= 1")
        manifest: List[Dict[str, Any]] = []
        payloads: List[bytes] = []
        for name, arr in self.arrays.items():
            arr = np.ascontiguousarray(arr)
            raw = arr.tobytes()
            crcs = [
                zlib.crc32(raw[i:i + chunk_bytes])
                for i in range(0, max(len(raw), 1), chunk_bytes)
            ]
            manifest.append({
                "name": name,
                "dtype": str(arr.dtype),
                "shape": list(arr.shape),
                "nbytes": len(raw),
                "chunk_bytes": chunk_bytes,
                "crcs": crcs,
            })
            payloads.append(raw)
        header = dict(self.header)
        header["version"] = VERSION
        header["arrays"] = manifest
        hj = json.dumps(header).encode()
        return b"".join([MAGIC, len(hj).to_bytes(4, "big"), hj] + payloads)

    @classmethod
    def deserialize(cls, data: bytes) -> "MigrationBlob":
        """Parse + integrity-check a serialized blob. Every failure is
        a ValueError naming what broke — corrupt KV must be refused at
        the door, never decoded into a pool."""
        if len(data) < len(MAGIC) + 4 or data[:len(MAGIC)] != MAGIC:
            raise ValueError("not a KV migration blob (bad magic)")
        off = len(MAGIC)
        hlen = int.from_bytes(data[off:off + 4], "big")
        off += 4
        if off + hlen > len(data):
            raise ValueError("KV blob truncated inside the header")
        try:
            header = json.loads(data[off:off + hlen])
        except ValueError as e:
            raise ValueError(f"KV blob header is not valid JSON: {e}")
        off += hlen
        if header.get("version") != VERSION:
            raise ValueError(
                f"KV blob version {header.get('version')!r}; this "
                f"build speaks version {VERSION}"
            )
        arrays: Dict[str, np.ndarray] = {}
        for ent in header.get("arrays", ()):
            n = int(ent["nbytes"])
            raw = data[off:off + n]
            if len(raw) != n:
                raise ValueError(
                    f"KV blob truncated inside array {ent['name']!r} "
                    f"(want {n} bytes, have {len(raw)})"
                )
            cb = int(ent["chunk_bytes"])
            crcs = ent["crcs"]
            for j in range(len(crcs)):
                chunk = raw[j * cb:(j + 1) * cb]
                if zlib.crc32(chunk) != crcs[j]:
                    raise ValueError(
                        f"KV blob chunk {j} of array {ent['name']!r} "
                        "failed its crc32 (corrupt transfer)"
                    )
            arrays[ent["name"]] = np.frombuffer(
                raw, dtype=_np_dtype(ent["dtype"])
            ).reshape(ent["shape"])
            off += n
        if off != len(data):
            raise ValueError(
                f"KV blob carries {len(data) - off} trailing bytes "
                "past its manifest"
            )
        return cls(header, arrays)


# ---------------------------------------------------------------------
# Export (prefill replica, engine-owning thread)
# ---------------------------------------------------------------------


def _check_exportable(engine) -> None:
    from shellac_tpu.inference.spec_batching import _SpecDecodeMixin

    if isinstance(engine, _SpecDecodeMixin):
        # The draft model's cache is unshipped state: an exported slot
        # would adopt with a desynced draft, and an imported one would
        # verify against a draft that never saw the prompt. Refused on
        # BOTH sides (this check guards export and import alike).
        raise ValueError(
            "KV migration does not support speculative engines (the "
            "draft model's cache does not migrate); serve draft-model "
            "replicas monolithically"
        )
    name = engine.cache_backend.name
    if name not in SUPPORTED_BACKENDS:
        raise ValueError(
            f"KV migration does not support the {name!r} backend"
        )
    kind = type(engine._cache).__name__
    if "Patterned" in kind:
        raise ValueError(
            "KV migration does not support patterned local/global "
            "rolling caches (mixed ring/dense rows per layer); use a "
            "uniform-window or dense backend, or serve monolithically"
        )


def _request_state(req, eos_id):
    """(state dict, complete?) — the request's JSON-serializable half:
    everything the importer needs to rebuild an identical _Request and
    slot sampling state."""
    out = list(req.out)
    lps = list(req.lps)
    tlp = req.tlp
    nstop = req.hit_stop()
    if nstop is not None:
        out = out[:-nstop]
        lps = lps[:len(out)]
        if tlp is not None:
            tlp = tlp[:len(out)]
    complete = (
        nstop is not None
        or (eos_id is not None and out and out[-1] == eos_id)
        or len(out) >= req.max_new
    )
    state: Dict[str, Any] = {
        "tokens": [int(t) for t in req.tokens],
        "max_new": int(req.max_new),
        "stop": req.stop,
        "temperature": req.temperature,
        "top_k": req.top_k,
        "top_p": req.top_p,
        "min_p": req.min_p,
        "min_tokens": req.min_tokens,
        "presence_penalty": req.presence_penalty,
        "frequency_penalty": req.frequency_penalty,
        "seed": req.seed,
        "logit_bias": ({str(k): v for k, v in req.logit_bias.items()}
                       if req.logit_bias else None),
        "prompt_logprobs": bool(req.prompt_logprobs),
        "out": [int(t) for t in out],
        "lps": [float(x) for x in lps],
        "tlp": ([[list(ids), [float(v) for v in vals]]
                 for ids, vals in tlp] if tlp is not None else None),
        # By export time the prefill is complete, so plp (when the
        # request scored its prompt) is the stitched flat float list.
        "plp": (None if req.plp is None
                else [float(x) for x in req.plp]),
    }
    return state, complete


def export_slot(engine, slot: int, req,
                trace_id: Optional[str] = None) -> MigrationBlob:
    """Serialize the frozen prefill-only request in `slot` (caller must
    be the engine-owning thread). The slot is NOT released here — the
    caller releases after the host copies below exist (device_get),
    so a failed export leaves a slot the caller can still clean up.

    A request already complete at its prefill (max_new=1, instant EOS,
    or a stop match on the first token) exports with `complete: true`
    and NO device payload — the importer settles it without touching
    its pool."""
    _check_exportable(engine)
    backend = engine.cache_backend
    state, complete = _request_state(req, engine.eos_id)
    # Physical KV residency: after n_out emitted tokens the slot holds
    # the prompt plus (n_out - 1) generated positions — the latest
    # token lives in _cur and writes its KV on the NEXT decode tick.
    # At the prefill_only freeze (n_out == 1) this is exactly the old
    # prompt-length export; a mid-decode preemption export ships the
    # decoded positions too.
    length = int(req.tokens.size) + max(len(req.out) - 1, 0)
    header: Dict[str, Any] = {
        "backend": backend.name,
        "kv_quant": engine.kv_quant,
        "model": model_fingerprint(engine),
        "engine": _engine_agreement(engine),
        "length": length,
        "complete": complete,
        "request": state,
        "residency": backend.residency(),
        "trace_id": trace_id,
    }
    if complete:
        return MigrationBlob(header, {})
    fields = kv_field_names(engine.kv_quant)
    cache = engine._cache
    if backend.is_paged:
        bs = backend.block_size
        nb_used = -(-length // bs)
        blocks = backend._slot_blocks[slot][:nb_used]
        if len(blocks) < nb_used:
            raise ValueError(
                f"slot {slot} holds {len(blocks)} blocks but its "
                f"{length} resident tokens need {nb_used} — allocator "
                "state desynced from the request"
            )
        header["block_size"] = bs
        header["n_blocks"] = nb_used
        idx = jnp.asarray(blocks, jnp.int32)
        pulls = {f: getattr(cache, f)[:, idx] for f in fields}
    elif backend.is_rolling:
        # The ring is window-sized and positions wrap: ship the WHOLE
        # ring row verbatim (content-at-ring-slot is the state).
        header["ring"] = int(cache.ring)
        pulls = {f: getattr(cache, f)[:, slot] for f in fields}
    else:
        pulls = {f: getattr(cache, f)[:, slot, :, :length]
                 for f in fields}
    # ONE blocking pull for the whole slot: the export is the admission
    # path's tail, never the decode hot loop.
    host = jax.device_get(pulls)  # shellac: ignore[SH002] — the migration export's single batched pull; the KV must reach the host to go on the wire
    return MigrationBlob(header, {f: np.asarray(a)
                                  for f, a in host.items()})


# ---------------------------------------------------------------------
# Import (decode replica, engine-owning thread)
# ---------------------------------------------------------------------


def _validate_import(engine, header: Dict[str, Any]) -> None:
    _check_exportable(engine)
    backend = engine.cache_backend
    if header.get("backend") != backend.name:
        raise ValueError(
            f"KV blob is for backend {header.get('backend')!r}; this "
            f"engine runs {backend.name!r} (cross-backend migration "
            "is refused — the storage layouts differ)"
        )
    fp = model_fingerprint(engine)
    if header.get("model") != fp:
        raise ValueError(
            f"KV blob model geometry {header.get('model')} does not "
            f"match this engine's {fp}"
        )
    agree = _engine_agreement(engine)
    if header.get("engine") != agree:
        raise ValueError(
            f"KV blob engine contract {header.get('engine')} does not "
            f"match this engine's {agree} (eos/logprobs settings must "
            "agree across a disaggregated pair)"
        )
    if backend.is_paged and header.get("block_size") != backend.block_size:
        raise ValueError(
            f"KV blob pages are {header.get('block_size')} tokens; "
            f"this pool uses {backend.block_size} (block_size must "
            "match across a disaggregated pair)"
        )


def import_blob(engine, blob: MigrationBlob, rid: Any,
                trace: Optional[Any] = None) -> int:
    """Adopt one INCOMPLETE migrated request into a free slot (caller
    must be the engine-owning thread; complete blobs settle without an
    engine — see the server's import path). Returns the slot.

    Raises PoolExhausted when no slot (or no pool capacity) is free —
    the retryable class; ValueError for a blob this engine must refuse
    (wrong backend/geometry/contract)."""
    header = blob.header
    _validate_import(engine, header)
    if header.get("complete"):
        raise ValueError("complete blobs carry no KV to import")
    backend = engine.cache_backend
    r = header["request"]

    slot = next(
        (i for i, occ in enumerate(engine._slots)
         if occ is None and i not in engine._prefilling),
        None,
    )
    if slot is None:
        raise PoolExhausted()

    # Rebuild the request through submit() so every validation (budget
    # vs max_len, sampling ranges, seed folding, logit_bias bounds)
    # applies to imported state exactly as it would to a local
    # admission — then pop it straight off the queue into the slot.
    engine.submit(
        rid, np.asarray(r["tokens"], np.int32), int(r["max_new"]),
        stop=r.get("stop"),
        temperature=r.get("temperature"), top_k=r.get("top_k"),
        top_p=r.get("top_p"), min_p=r.get("min_p"),
        min_tokens=r.get("min_tokens"),
        logit_bias=({int(k): float(v)
                     for k, v in r["logit_bias"].items()}
                    if r.get("logit_bias") else None),
        presence_penalty=r.get("presence_penalty"),
        frequency_penalty=r.get("frequency_penalty"),
        prompt_logprobs=bool(r.get("prompt_logprobs")),
        seed=r.get("seed"), trace=trace,
    )
    req = engine._queue.pop()
    req.out = [int(t) for t in r["out"]]
    req.lps = [float(x) for x in r.get("lps") or ()]
    if r.get("tlp") is not None:
        req.tlp = [(list(ids), list(vals)) for ids, vals in r["tlp"]]
    if r.get("plp") is not None:
        req.plp = r["plp"]
    if not req.out:
        raise ValueError("KV blob carries no generated tokens")
    length = int(header["length"])

    try:
        return _place_slot(engine, backend, blob, header, req, rid,
                           slot, length, trace)
    except Exception:
        # A failure past block reservation (malformed manifest, a
        # shape-mismatched array) must not leak pool blocks or
        # half-written slot sampling state: release exactly like a
        # cancel — the slot was never occupied, so there is nothing
        # else to unwind.
        engine._slots[slot] = None
        engine._release_slot(slot)
        raise


def _place_slot(engine, backend, blob, header, req, rid, slot,
                length, trace) -> int:
    """Device writes + the _finish_prefill host-bookkeeping mirror for
    one validated import (import_blob's guarded tail)."""
    # ---- device writes ----------------------------------------------
    fields = kv_field_names(engine.kv_quant)
    cache = engine._cache
    if backend.is_paged:
        if not backend.ensure_blocks(slot, engine._slot_footprint(req)):
            raise PoolExhausted()
        nb = int(header["n_blocks"])
        blocks = backend._slot_blocks[slot][:nb]
        idx = jnp.asarray(blocks, jnp.int32)
        # Re-read after ensure_blocks rebound the tables.
        cache = engine._cache
        new = {
            f: getattr(cache, f).at[:, idx].set(
                jnp.asarray(blob.arrays[f])
            )
            for f in fields
        }
    elif backend.is_rolling:
        if int(header.get("ring", -1)) != int(cache.ring):
            raise ValueError(
                f"KV blob ring size {header.get('ring')} does not "
                f"match this engine's ring {int(cache.ring)}"
            )
        new = {
            f: getattr(cache, f).at[:, slot].set(
                jnp.asarray(blob.arrays[f])
            )
            for f in fields
        }
    else:
        new = {
            f: getattr(cache, f).at[:, slot, :, :length].set(
                jnp.asarray(blob.arrays[f])
            )
            for f in fields
        }
    new["lengths"] = cache.lengths.at[slot].set(length)
    engine._cache = cache.replace(**new)

    # ---- host bookkeeping (the _finish_prefill mirror) --------------
    n_out = len(req.out)
    engine._cur = engine._cur.at[slot].set(int(req.out[-1]))
    engine._srem = engine._srem.at[slot].set(
        max(req.max_new - n_out, 0)
    )
    engine._sdone = engine._sdone.at[slot].set(False)
    engine._set_slot_sampling(slot, req)
    if req.constraint is not None:  # unreachable: submit refuses above
        raise ValueError("constrained requests do not migrate")
    if engine._slot_pen[slot]:
        for t in req.out:
            engine._scounts = engine._scounts.at[slot, int(t)].add(1.0)
    if req.min_tokens > 0:
        engine._smin = engine._smin.at[slot].set(
            max(req.min_tokens - n_out, 0)
        )
    engine._slots[slot] = req
    engine.stats["kv_imports"] += 1
    if trace is not None:
        # Decode-side span marks: queue wait ends at adoption, and the
        # first token already exists (it crossed on the wire) — the
        # importer's TTFT is honest about that.
        trace.prefill_start()
        trace.first_token()
        trace.record("kv-import", src="engine", rid=rid, slot=slot,
                     backend=backend.name, tokens=length,
                     n_out=n_out)
    return slot
