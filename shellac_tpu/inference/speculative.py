"""Speculative decoding: draft-model proposal + target verification.

Decode is HBM-bound, so the target model's per-token cost is dominated by
re-reading its weights. Speculative decoding (Leviathan et al., 2022)
amortizes that read: a small draft model proposes `gamma` tokens
autoregressively, then the target scores all gamma+1 positions in ONE
forward pass (an MXU-friendly batched matmul instead of gamma small
ones) and accepts a prefix via rejection sampling. The emitted
distribution is mathematically identical to sampling the target alone.

TPU-first structure — everything is static-shape and stays on device:
  - the round loop is a `lax.while_loop`; each round emits between 1 and
    gamma+1 tokens per sequence (batch entries advance unevenly, tracked
    by per-sequence write offsets into a slack-padded output buffer);
  - rejected tokens are "rolled back" by clamping the KV cache's
    per-sequence `lengths` — stale entries are overwritten on the next
    write at that offset (see kvcache.py), no copies;
  - after its gamma sampled steps the draft runs one backfill step on
    its last proposal so that, when every token is accepted, the draft
    cache already holds the full history for the next round.

Temperature 0 uses the exact-match degenerate form (accept iff the draft
token equals the target argmax), which makes greedy speculative output
EXACTLY equal to greedy target-only decoding — the main correctness test.

The reference repo for this project is empty (SURVEY.md §0); there is no
upstream speculative decoder to cite.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.kvcache import KVCache, init_cache
from shellac_tpu.models import transformer
from shellac_tpu.ops.sampling import sample


@flax.struct.dataclass
class SpecResult:
    tokens: jax.Array  # (B, max_new_tokens) int32 — target-distributed
    rounds: jax.Array  # () int32 — verification rounds run
    accept_rate: jax.Array  # () fp32 — accepted draft tokens / proposed


def _probs(logits: jax.Array, temperature: float) -> jax.Array:
    return jax.nn.softmax(logits.astype(jnp.float32) / temperature, axis=-1)


class SpeculativeEngine:
    """Paired target/draft engine. Models must share the vocabulary."""

    def __init__(
        self,
        cfg: ModelConfig,
        params: Any,
        draft_cfg: ModelConfig,
        draft_params: Any,
        *,
        gamma: int = 4,
        temperature: float = 1.0,
        max_len: Optional[int] = None,
    ):
        if cfg.vocab_size != draft_cfg.vocab_size:
            raise ValueError(
                f"target/draft vocab mismatch: {cfg.vocab_size} vs "
                f"{draft_cfg.vocab_size}"
            )
        if gamma < 1:
            raise ValueError(f"gamma must be >= 1, got {gamma}")
        self.cfg = cfg
        self.draft_cfg = draft_cfg
        self.params = params
        self.draft_params = draft_params
        self.gamma = gamma
        self.temperature = float(temperature)
        self.max_len = max_len or min(cfg.max_seq_len, draft_cfg.max_seq_len)
        self._gen = jax.jit(self._generate_impl, static_argnums=(4,))

    # ---- one verification round -------------------------------------

    def _draft_propose(self, draft_params, dcache, cur, key):
        """gamma sampled draft steps + one cache-backfill step.

        Returns (dcache, drafts (B, gamma) int32, q (B, gamma, V) fp32).
        """
        g = self.gamma

        def step(carry, k):
            dc, tok = carry
            logits, dc = transformer.forward_with_cache(
                self.draft_cfg, draft_params, tok[:, None], dc
            )
            logits = logits[:, 0]
            nxt = sample(k, logits, temperature=self.temperature)
            q = _probs(logits, self.temperature or 1.0)
            return (dc, nxt), (nxt, q)

        (dcache, _), (drafts, qs) = jax.lax.scan(
            step, (dcache, cur), jax.random.split(key, g)
        )
        # Backfill: write the last proposal's kv so the all-accepted case
        # leaves the draft cache complete for the next round.
        _, dcache = transformer.forward_with_cache(
            self.draft_cfg, draft_params, drafts[-1][:, None], dcache
        )
        return dcache, drafts.T, jnp.moveaxis(qs, 0, 1)  # (B,g), (B,g,V)

    def _round(self, params, draft_params, carry):
        (tcache, dcache, cur, out, out_len, key, n_acc, n_prop, rounds,
         max_new) = carry
        g = self.gamma
        b = cur.shape[0]
        key, kd, kacc, kres, kbonus = jax.random.split(key, 5)

        lt0 = tcache.lengths  # target history length before this round
        ld0 = dcache.lengths

        dcache, drafts, qs = self._draft_propose(draft_params, dcache, cur, kd)

        # Target scores [cur, d_0..d_{g-1}] in one forward: logits[:, i]
        # is the target distribution for position i's successor.
        tin = jnp.concatenate([cur[:, None], drafts], axis=1)  # (B, g+1)
        tlogits, tcache = transformer.forward_with_cache(
            self.cfg, params, tin, tcache
        )
        ps = _probs(tlogits, self.temperature or 1.0)  # (B, g+1, V)

        p_d = jnp.take_along_axis(ps[:, :g], drafts[..., None], axis=-1)[..., 0]
        q_d = jnp.take_along_axis(qs, drafts[..., None], axis=-1)[..., 0]
        if self.temperature == 0.0:
            accept = drafts == jnp.argmax(ps[:, :g], axis=-1)
        else:
            u = jax.random.uniform(kacc, (b, g))
            accept = u * q_d < p_d
        # Length of the accepted prefix: 0..g per sequence.
        n = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1), axis=1)

        # Token emitted after the accepted prefix: residual resample on
        # rejection, bonus sample from the g+1'th target dist otherwise.
        idx = jnp.minimum(n, g - 1)
        p_n = jnp.take_along_axis(ps, idx[:, None, None], axis=1)[:, 0]  # (B,V)
        q_n = jnp.take_along_axis(qs, idx[:, None, None], axis=1)[:, 0]
        if self.temperature == 0.0:
            # Degenerate (one-hot) form: a rejected position emits the
            # target's own argmax, not the continuous-residual argmax.
            r = jnp.argmax(p_n, axis=-1).astype(jnp.int32)
            bonus = jnp.argmax(ps[:, g], axis=-1).astype(jnp.int32)
        else:
            res = jnp.maximum(p_n - q_n, 0.0)
            res_mass = jnp.sum(res, axis=-1, keepdims=True)
            # p == q pointwise means rejection has probability 0; the
            # guard only protects against fp rounding making a zero row.
            res = jnp.where(res_mass > 1e-9, res, p_n)
            r = jax.random.categorical(kres, jnp.log(res + 1e-30)).astype(
                jnp.int32
            )
            bonus = jax.random.categorical(
                kbonus, jnp.log(ps[:, g] + 1e-30)
            ).astype(jnp.int32)
        extra = jnp.where(n < g, r, bonus)

        # Emitted chunk (B, g+1): accepted drafts then `extra` at col n;
        # columns past n are garbage that later rounds overwrite.
        cols = jnp.arange(g + 1, dtype=jnp.int32)[None, :]
        padded = jnp.concatenate([drafts, extra[:, None]], axis=1)
        emitted = jnp.where(cols == n[:, None], extra[:, None], padded)

        done = out_len >= max_new
        # Roll back: valid history = old length + 1 (cur) + n accepted;
        # finished sequences freeze entirely.
        new_tlen = jnp.where(done, lt0, lt0 + 1 + n)
        new_dlen = jnp.where(done, ld0, ld0 + 1 + n)
        tcache = KVCache(k=tcache.k, v=tcache.v, lengths=new_tlen)
        dcache = KVCache(k=dcache.k, v=dcache.v, lengths=new_dlen)
        cur = jnp.where(done, cur, extra)

        offset = jnp.minimum(out_len, max_new)  # done rows write to slack
        out = jax.vmap(
            lambda row, chunk, i: jax.lax.dynamic_update_slice(row, chunk, (i,))
        )(out, emitted, offset)
        out_len = jnp.where(done, out_len, out_len + n + 1)
        live = (~done).astype(jnp.int32)
        n_acc = n_acc + jnp.sum(n * live)
        n_prop = n_prop + jnp.sum(live) * g
        return (tcache, dcache, cur, out, out_len, key, n_acc, n_prop,
                rounds + 1, max_new)

    # ---- generation --------------------------------------------------

    def _generate_impl(self, params, draft_params, tokens, prompt_len,
                       max_new, key):
        b, s = tokens.shape
        g = self.gamma
        tcache = init_cache(self.cfg, b, self.max_len)
        dcache = init_cache(self.draft_cfg, b, self.max_len)
        tlogits, tcache = transformer.forward_with_cache(
            self.cfg, params, tokens, tcache, new_tokens_len=prompt_len,
            fresh_cache=True, attn_impl="auto",
        )
        _, dcache = transformer.forward_with_cache(
            self.draft_cfg, draft_params, tokens, dcache,
            new_tokens_len=prompt_len, fresh_cache=True, attn_impl="auto",
        )
        last = jnp.take_along_axis(
            tlogits, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
        key, k0 = jax.random.split(key)
        cur = sample(k0, last, temperature=self.temperature)

        out = jnp.zeros((b, max_new + g + 1), jnp.int32)
        # The token sampled from prefill is the first output.
        out = out.at[:, 0].set(cur)
        out_len = jnp.ones((b,), jnp.int32)
        zero = jnp.zeros((), jnp.int32)
        carry = (tcache, dcache, cur, out, out_len, key, zero, zero, zero,
                 jnp.asarray(max_new, jnp.int32))

        def cond(c):
            return jnp.any(c[4] < c[9])

        carry = jax.lax.while_loop(
            cond, functools.partial(self._round, params, draft_params), carry
        )
        (_, _, _, out, _, _, n_acc, n_prop, rounds, _) = carry
        rate = n_acc.astype(jnp.float32) / jnp.maximum(
            n_prop.astype(jnp.float32), 1.0
        )
        return SpecResult(
            tokens=out[:, :max_new], rounds=rounds, accept_rate=rate
        )

    def generate(
        self,
        prompt_tokens: jax.Array,  # (B, S) int32, right-padded
        prompt_len: Optional[jax.Array] = None,
        *,
        max_new_tokens: int = 32,
        key: Optional[jax.Array] = None,
    ) -> SpecResult:
        if key is None:
            key = jax.random.PRNGKey(0)
        b, s = prompt_tokens.shape
        if prompt_len is None:
            prompt_len = jnp.full((b,), s, jnp.int32)
        # Worst case: a finished row freezes its cache length at up to
        # s + max_new + gamma - 1 and later rounds still write gamma+1
        # entries there, so reserve s + max_new + 2*gamma slots (+2 slack)
        # to keep those writes off the valid prefix.
        need = s + max_new_tokens + 2 * self.gamma + 2
        if need > self.max_len:
            raise ValueError(
                f"prompt ({s}) + max_new_tokens ({max_new_tokens}) + "
                f"gamma slack needs cache length {need} > max_len "
                f"{self.max_len}"
            )
        return self._gen(
            self.params, self.draft_params, prompt_tokens, prompt_len,
            max_new_tokens, key,
        )
