"""Seeded, deterministic production-traffic model.

Every load test in this repo so far drove ONE synthetic shape at a
time (the chaos scenarios' uniform closed loops, the bench gate's
fixed churn). Real fleets are a superposition: a heavy-head/long-tail
tenant population, open-loop arrivals that do not slow down because
the server did, traffic bursts and diurnal ramps, prompt lengths with
a 32k+ tail that lands on chunked prefill, and a request-type mix —
streaming chats that get cancelled mid-flight, tool/constrained
calls, prefill-heavy summarization, and shared-system-prompt traffic
whose prefix the KV fabric should be deduplicating.

`WorkloadConfig` declares that superposition; `WorkloadModel` turns
it into a concrete, fully deterministic *schedule* — a list of
`RequestSpec`s with absolute arrival offsets — using one
`random.Random(seed)` stream. Determinism is a contract, not an
accident: the scenario gate commits a fingerprint of the schedule
(`WorkloadModel.fingerprint()`) to `SCENARIO_LEDGER.json`, so a
config edit that changes the traffic a scenario asserts its SLOs
under shows up as ledger drift in CI, never silently.

Two deliberate modeling choices keep the fingerprint portable:

- Arrivals are an inhomogeneous Poisson process sampled by Lewis &
  Shedler thinning — candidate points at the peak rate, each kept
  with probability rate(t)/peak — so the schedule is exact for any
  rate curve and needs only `Random.expovariate`/`random`.
- The diurnal ramp is a triangle wave, not a sine: pure arithmetic,
  so the schedule never depends on the platform's libm and the
  committed fingerprint is stable across machines.

The DEFAULT config is the production shape (32k tail and all); CI
scenarios (`inference/scenarios.py`) override it down to seconds of
traffic against the tiny model. Scaling the config down scales the
schedule, not the model.
"""

from __future__ import annotations

import hashlib
import json
import random
from dataclasses import dataclass, field, replace
from typing import Dict, List, Mapping, Optional, Tuple

#: The request kinds a schedule can mix. Each maps to a concrete
#: /generate payload shape in `RequestSpec.payload()`:
#:   chat          — non-streaming completion
#:   stream        — NDJSON streaming completion, read to the end
#:   stream_cancel — streaming, client severs after a few deltas
#:   tool          — constrained decode (PR 8's DFA path)
#:   prefill_heavy — long prompt, tiny completion (summarization)
#:   shared_prefix — shared system prompt + short user suffix (the
#:                   prefix-reuse traffic the KV fabric dedups)
REQUEST_KINDS = ("chat", "stream", "stream_cancel", "tool",
                 "prefill_heavy", "shared_prefix")


@dataclass(frozen=True)
class Burst:
    """One traffic burst: rate multiplied by `multiplier` for
    `duration_s` starting at `start_s` (offsets from run start)."""

    start_s: float
    duration_s: float
    multiplier: float

    def validate(self) -> None:
        if self.start_s < 0 or self.duration_s <= 0:
            raise ValueError(
                f"burst needs start_s >= 0 and duration_s > 0 "
                f"(got {self.start_s}, {self.duration_s})"
            )
        if self.multiplier <= 0:
            raise ValueError(
                f"burst multiplier must be > 0 (got {self.multiplier})"
            )


@dataclass(frozen=True)
class Diurnal:
    """Triangle-wave rate modulation: factor ranges over
    [1-amplitude, 1+amplitude] with period `period_s`, peaking at
    `period_s/2` past each period start. A triangle (not a sine) so
    the schedule stays libm-free and bit-stable across platforms."""

    amplitude: float
    period_s: float

    def validate(self) -> None:
        if not 0.0 <= self.amplitude < 1.0:
            raise ValueError(
                f"diurnal amplitude must be in [0, 1) "
                f"(got {self.amplitude})"
            )
        if self.period_s <= 0:
            raise ValueError(
                f"diurnal period_s must be > 0 (got {self.period_s})"
            )

    def factor(self, t: float) -> float:
        # Triangle wave in [-1, 1]: -1 at period start, +1 at half
        # period. Pure arithmetic on purpose.
        x = (t % self.period_s) / self.period_s          # [0, 1)
        tri = 1.0 - 4.0 * abs(x - 0.5)                   # [-1, 1]
        return 1.0 + self.amplitude * tri


@dataclass(frozen=True)
class WorkloadConfig:
    """Declarative traffic model. Defaults describe the production
    shape; scenarios override them down to CI scale. `validate()`
    runs eagerly in `WorkloadModel` so a bad config dies at registry
    build, not mid-run."""

    seed: int = 0
    duration_s: float = 3600.0
    base_rate: float = 50.0                 # mean arrivals/second
    #: Tenant population, list order = popularity rank (Zipf head
    #: first). PR 18's tenant identity rides the x-shellac-tenant
    #: header on every request.
    tenants: Tuple[str, ...] = ("acme", "globex", "initech",
                                "umbrella", "hooli", "wonka",
                                "stark", "tyrell")
    zipf_s: float = 1.2
    bursts: Tuple[Burst, ...] = ()
    diurnal: Optional[Diurnal] = Diurnal(amplitude=0.5,
                                         period_s=86400.0)
    #: Request-type mix, kind -> weight (normalized internally).
    mix: Mapping[str, float] = field(default_factory=lambda: {
        "chat": 0.30, "stream": 0.25, "stream_cancel": 0.05,
        "tool": 0.15, "prefill_heavy": 0.10, "shared_prefix": 0.15,
    })
    #: Prompt-length buckets: (lo, hi, weight) in tokens, sampled
    #: uniformly inside the chosen bucket.
    prompt_buckets: Tuple[Tuple[int, int, float], ...] = (
        (8, 64, 0.55), (64, 512, 0.30), (512, 4096, 0.15),
    )
    #: The long tail: with probability tail_p the prompt is
    #: tail_len tokens — the 32k+ case chunked prefill exists for.
    tail_p: float = 0.02
    tail_len: int = 32768
    max_new: Tuple[int, int] = (4, 64)      # uniform, inclusive
    #: prefill_heavy caps its completion here (long in, short out).
    prefill_heavy_max_new: int = 4
    #: stream_cancel severs after this many delta lines (uniform).
    cancel_after_deltas: Tuple[int, int] = (1, 3)
    shared_prefix_len: int = 64
    #: Token-id range for synthetic prompts (byte tokenizer safe).
    vocab: int = 200
    #: Regex the tool kind constrains decode to (tiny on purpose:
    #: the DFA compile walks the vocab once, then caches).
    tool_regex: str = "(yes|no|maybe)"

    def validate(self) -> None:
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0 (got {self.duration_s})")
        if self.base_rate <= 0:
            raise ValueError(
                f"base_rate must be > 0 (got {self.base_rate})")
        if not self.tenants:
            raise ValueError("tenants must be non-empty")
        if self.zipf_s < 0:
            raise ValueError(f"zipf_s must be >= 0 (got {self.zipf_s})")
        for b in self.bursts:
            b.validate()
        if self.diurnal is not None:
            self.diurnal.validate()
        if not self.mix:
            raise ValueError("mix must be non-empty")
        for kind, w in self.mix.items():
            if kind not in REQUEST_KINDS:
                raise ValueError(
                    f"unknown request kind {kind!r} in mix "
                    f"(known: {', '.join(REQUEST_KINDS)})"
                )
            if w < 0:
                raise ValueError(
                    f"mix weight for {kind!r} must be >= 0 (got {w})")
        if sum(self.mix.values()) <= 0:
            raise ValueError("mix weights must sum > 0")
        if not self.prompt_buckets:
            raise ValueError("prompt_buckets must be non-empty")
        for lo, hi, w in self.prompt_buckets:
            if not (1 <= lo <= hi):
                raise ValueError(
                    f"prompt bucket needs 1 <= lo <= hi (got {lo}, {hi})")
            if w < 0:
                raise ValueError(
                    f"prompt bucket weight must be >= 0 (got {w})")
        if sum(w for _, _, w in self.prompt_buckets) <= 0:
            raise ValueError("prompt bucket weights must sum > 0")
        if not 0.0 <= self.tail_p <= 1.0:
            raise ValueError(
                f"tail_p must be in [0, 1] (got {self.tail_p})")
        if self.tail_len < 1:
            raise ValueError(
                f"tail_len must be >= 1 (got {self.tail_len})")
        lo, hi = self.max_new
        if not (1 <= lo <= hi):
            raise ValueError(
                f"max_new needs 1 <= lo <= hi (got {self.max_new})")
        lo, hi = self.cancel_after_deltas
        if not (1 <= lo <= hi):
            raise ValueError(
                "cancel_after_deltas needs 1 <= lo <= hi "
                f"(got {self.cancel_after_deltas})"
            )
        if self.shared_prefix_len < 1:
            raise ValueError(
                f"shared_prefix_len must be >= 1 "
                f"(got {self.shared_prefix_len})"
            )
        if self.vocab < 2:
            raise ValueError(f"vocab must be >= 2 (got {self.vocab})")
        if self.prefill_heavy_max_new < 1:
            raise ValueError(
                "prefill_heavy_max_new must be >= 1 "
                f"(got {self.prefill_heavy_max_new})"
            )

    def scaled(self, factor: float) -> "WorkloadConfig":
        """A copy with duration scaled by `factor` (burst offsets and
        diurnal period scale with it so the shape is preserved)."""
        if factor <= 0:
            raise ValueError(f"scale factor must be > 0 (got {factor})")
        bursts = tuple(
            Burst(b.start_s * factor, b.duration_s * factor,
                  b.multiplier)
            for b in self.bursts
        )
        diurnal = (Diurnal(self.diurnal.amplitude,
                           self.diurnal.period_s * factor)
                   if self.diurnal is not None else None)
        return replace(self, duration_s=self.duration_s * factor,
                       bursts=bursts, diurnal=diurnal)


@dataclass(frozen=True)
class RequestSpec:
    """One concrete request in a schedule. `payload()` renders the
    LoadGenerator-ready dict: the native /generate body plus the
    reserved client-side keys (`tenant`, `kind`,
    `cancel_after_deltas`) the generator strips before the wire."""

    arrival_s: float
    tenant: str
    kind: str
    tokens: Tuple[int, ...]
    max_new: int
    stream: bool
    cancel_after: Optional[int] = None
    constraint_regex: Optional[str] = None

    def payload(self, timeout: Optional[float] = None) -> Dict[str, object]:
        p: Dict[str, object] = {
            "tokens": list(self.tokens),
            "max_new": self.max_new,
            "tenant": self.tenant,
            "kind": self.kind,
        }
        if self.stream:
            p["stream"] = True
        if self.cancel_after is not None:
            p["cancel_after_deltas"] = self.cancel_after
        if self.constraint_regex is not None:
            p["constraint"] = {"regex": self.constraint_regex}
        if timeout is not None:
            p["timeout"] = timeout
        return p

    def row(self) -> Dict[str, object]:
        """Canonical projection for fingerprinting: every field that
        defines the request, floats rounded so the hash never hinges
        on sub-microsecond float formatting."""
        return {
            "arrival_s": round(self.arrival_s, 6),
            "tenant": self.tenant,
            "kind": self.kind,
            "tokens": list(self.tokens),
            "max_new": self.max_new,
            "stream": self.stream,
            "cancel_after": self.cancel_after,
            "constraint_regex": self.constraint_regex,
        }


class WorkloadModel:
    """Turn a `WorkloadConfig` into a deterministic schedule.

    One `random.Random(seed)` stream drives everything — arrivals,
    tenant draws, kind draws, prompt lengths, token ids — so the
    whole schedule is a pure function of the config. `schedule()` is
    computed once and cached; `fingerprint()` hashes its canonical
    JSON projection."""

    def __init__(self, config: WorkloadConfig):
        config.validate()
        self.config = config
        self._schedule: Optional[List[RequestSpec]] = None
        # The shared system prompt: fixed tokens derived from the
        # seed (NOT drawn from the arrival stream, so every
        # shared_prefix request in one schedule shares it exactly).
        prng = random.Random(f"{config.seed}:shared-prefix")
        self._shared_prefix = tuple(
            prng.randrange(config.vocab)
            for _ in range(config.shared_prefix_len)
        )

    # ---- rate curve --------------------------------------------------

    def rate_at(self, t: float) -> float:
        """Instantaneous arrival rate (req/s) at offset `t`."""
        cfg = self.config
        rate = cfg.base_rate
        if cfg.diurnal is not None:
            rate *= cfg.diurnal.factor(t)
        for b in cfg.bursts:
            if b.start_s <= t < b.start_s + b.duration_s:
                rate *= b.multiplier
        return rate

    def peak_rate(self) -> float:
        """Upper bound on rate_at over the run — the thinning
        envelope. Bursts may overlap, so multipliers compound."""
        cfg = self.config
        peak = cfg.base_rate
        if cfg.diurnal is not None:
            peak *= 1.0 + cfg.diurnal.amplitude
        for b in cfg.bursts:
            if b.multiplier > 1.0 and b.start_s < cfg.duration_s:
                peak *= b.multiplier
        return peak

    # ---- sampling ----------------------------------------------------

    def _draw_tenant(self, rng: random.Random) -> str:
        cfg = self.config
        weights = [1.0 / (r + 1) ** cfg.zipf_s
                   for r in range(len(cfg.tenants))]
        return rng.choices(cfg.tenants, weights=weights)[0]

    def _draw_kind(self, rng: random.Random) -> str:
        kinds = list(self.config.mix.keys())
        weights = [self.config.mix[k] for k in kinds]
        return rng.choices(kinds, weights=weights)[0]

    def _draw_prompt_len(self, rng: random.Random) -> int:
        cfg = self.config
        if cfg.tail_p > 0 and rng.random() < cfg.tail_p:
            return cfg.tail_len
        buckets = list(cfg.prompt_buckets)
        weights = [w for _, _, w in buckets]
        lo, hi, _ = rng.choices(buckets, weights=weights)[0]
        return rng.randint(lo, hi)

    def _make_spec(self, rng: random.Random, t: float) -> RequestSpec:
        cfg = self.config
        tenant = self._draw_tenant(rng)
        kind = self._draw_kind(rng)
        max_new = rng.randint(*cfg.max_new)
        cancel_after = None
        constraint = None
        stream = False
        if kind == "shared_prefix":
            # Shared system prompt + a short per-request suffix: the
            # prefix hash chain is identical across requests, which
            # is exactly what the fabric's dedup should catch.
            suffix_len = max(1, rng.randint(1, 8))
            tokens = self._shared_prefix + tuple(
                rng.randrange(cfg.vocab) for _ in range(suffix_len))
        else:
            n = self._draw_prompt_len(rng)
            if kind == "prefill_heavy":
                # Bias to the top of the distribution: long in,
                # short out.
                top_lo = max(lo for lo, _, _ in cfg.prompt_buckets)
                n = max(n, top_lo)
                max_new = min(max_new, cfg.prefill_heavy_max_new)
            tokens = tuple(rng.randrange(cfg.vocab) for _ in range(n))
        if kind in ("stream", "stream_cancel"):
            stream = True
        if kind == "stream_cancel":
            cancel_after = rng.randint(*cfg.cancel_after_deltas)
        if kind == "tool":
            constraint = cfg.tool_regex
        return RequestSpec(
            arrival_s=t, tenant=tenant, kind=kind, tokens=tokens,
            max_new=max_new, stream=stream, cancel_after=cancel_after,
            constraint_regex=constraint,
        )

    # ---- the schedule ------------------------------------------------

    def schedule(self) -> List[RequestSpec]:
        """The full deterministic schedule, sorted by arrival. Lewis-
        Shedler thinning: candidates at the peak rate, each kept with
        probability rate(t)/peak — exact for any rate curve."""
        if self._schedule is not None:
            return self._schedule
        cfg = self.config
        rng = random.Random(cfg.seed)
        peak = self.peak_rate()
        out: List[RequestSpec] = []
        t = 0.0
        while True:
            t += rng.expovariate(peak)
            if t >= cfg.duration_s:
                break
            # One uniform draw per candidate, accepted or not, keeps
            # the stream aligned however the rate curve changes.
            keep = rng.random() <= self.rate_at(t) / peak
            if keep:
                out.append(self._make_spec(rng, t))
        self._schedule = out
        return out

    def fingerprint(self) -> str:
        """sha256 of the schedule's canonical JSON — the ledger's
        drift detector for 'the traffic this scenario asserts its
        SLOs under changed'."""
        rows = [s.row() for s in self.schedule()]
        blob = json.dumps(rows, sort_keys=True,
                          separators=(",", ":")).encode()
        return hashlib.sha256(blob).hexdigest()

    def tenant_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.schedule():
            out[s.tenant] = out.get(s.tenant, 0) + 1
        return out

    def kind_counts(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for s in self.schedule():
            out[s.kind] = out.get(s.kind, 0) + 1
        return out

    def payload_schedule(self, timeout: Optional[float] = None
                         ) -> List[Tuple[float, Dict[str, object]]]:
        """(arrival_s, payload) pairs — LoadGenerator's open-loop
        input format."""
        return [(s.arrival_s, s.payload(timeout=timeout))
                for s in self.schedule()]
