"""Token-level pipelined decode for pp-mesh serving.

Plain pp serving (GSPMD layer sharding, one batched tick in flight)
leaves pp-1 stages idle at every instant: decode is strictly
sequential through the stages, so pp buys KV/weight capacity while
wasting the chips it adds. This module removes the idle time the same
way the training pipeline does (parallel/pipeline.py) — not with
per-stage programs, but with ONE scanned GSPMD program over a stage
register:

  - the n_slots slot batch splits into pp contiguous GROUPS of
    G = n_slots/pp slots;
  - a register holds per-stage activations (pp, G, 1, D), sharded over
    the `pp` mesh axis like the (pp, L/pp, ...) reshaped layer stack
    and KV cache;
  - each MICROTICK, `jax.vmap` over the stage axis applies every
    stage's layer block to the group it currently holds — pp different
    groups advance one stage each, concurrently, on their own devices;
  - the register then rolls one stage (XLA: collective-permute over
    ICI): the group leaving stage pp-1 is sampled, and the group whose
    token was just sampled re-enters at stage 0 next microtick.

Steady-state stage utilization is 100%: at microtick t, stage s works
on group (t - s) mod pp. A decode window of K tokens per slot costs
pp*K + (pp-1) microticks (the pp-1 tail is the drain ramp), against
pp*K stage-sequential units for the unpipelined tick — and each
microtick runs all stages in parallel, so wall-clock per window
approaches (K + 1) stage-times instead of pp*K.

Scope: dense bf16, int8, and rolling-ring caches over uniform layer
stacks, plus patterned stacks (Gemma-2/3, GPT-OSS) over the dense
caches — each stage holds whole pattern periods and the kinds unroll
inside the stage scan with dual rope. Excluded: first_k_dense /
moe_every layouts, paged pools, and the mixed PatternedKVCache
(patterned + rolling). int8 scale stacks ride the same stage split;
ring wrap stays bit-exact because stale one-ahead writes alias only
positions outside every window. Each slot's math is row-for-row
identical to the unpipelined engine, so greedy output is bit-exact
(tests/test_pp_pipeline.py).

The reference repo for this project is empty (SURVEY.md §0); there is
no upstream pipelined-decoding implementation to cite. The schedule is
the classic round-robin token-level pipelining idea (public
literature: PipeDream-style weight-stationary decode), rebuilt for the
GSPMD/`lax.scan` compilation model.
"""

from __future__ import annotations

from typing import List, Optional

import jax

from shellac_tpu.config import ModelConfig
from shellac_tpu.models.transformer import (
    _block,
    _embed_tokens,
    pattern_period_scan,
    rope_angles,
    unembed,
)
from shellac_tpu.parallel.sharding import constrain

# Logical axes for the stage-reshaped buffers: leading axis is the
# stage ("layers" -> pp in the shared rule table); the slot batch is
# replicated in serving (the scheduler owns it).
_REG_AXES = ("layers", None, None, None)


def pp_schedule(pp: int, ticks: int) -> List[dict]:
    """The static microtick schedule, for tests and docs.

    Returns one dict per microtick t of a K=`ticks` decode window:
      enter: group entering stage 0 (None once entries stop),
      exit:  group leaving stage pp-1 (None during warmup),
      stages: {stage: group} for every stage holding a LIVE token.

    Live means the token both entered at a real entry microtick and
    will exit within the window (drain-tail entries never exit; their
    cache writes land at each slot's next position and are overwritten
    by that token's real pass in the following window).
    """
    total = pp * ticks + pp - 1
    out = []
    for t in range(total):
        stages = {}
        for s in range(pp):
            entered_at = t - s
            if 0 <= entered_at < pp * ticks:
                stages[s] = entered_at % pp
        out.append({
            "enter": t % pp if t < pp * ticks else None,
            "exit": (t - (pp - 1)) % pp if t >= pp - 1 else None,
            "stages": stages,
        })
    return out


def stage_split(tree, pp: int):
    """Reshape every (L, ...) leaf to (pp, L/pp, ...)."""
    return jax.tree.map(
        lambda a: a.reshape(pp, a.shape[0] // pp, *a.shape[1:]), tree
    )


def stage_merge(tree):
    """Inverse of stage_split: (pp, Lp, ...) -> (L, ...)."""
    return jax.tree.map(
        lambda a: a.reshape(a.shape[0] * a.shape[1], *a.shape[2:]), tree
    )


def embed_group(cfg: ModelConfig, params, tokens, mesh):
    """Embed one group's next tokens: (G,) int32 -> (G, 1, D)."""
    return _embed_tokens(
        cfg, params, tokens[:, None], cfg.compute_dtype, mesh=mesh
    )


def head_logits(cfg: ModelConfig, params, y):
    """Final norm + unembedding on one group's exit activations.

    y: (G, 1, D) -> (G, V) fp32. Defers to the SHARED model tail
    (transformer.unembed) so per-row logits are bit-identical to the
    unpipelined tick by construction.
    """
    return unembed(cfg, params, y)[:, 0]


def stage_apply(
    cfg: ModelConfig,
    mesh,
    attn_impl: str,
    stage_params,  # pytree, leaves (pp, Lp, ...)
    cache_st,  # tuple of stage-split cache stacks, batch at axis 2:
               # (k, v) bf16 — (pp, Lp, B, Hkv, len, Dh) — or
               # (k, v, ks, vs) int8, scale stacks (pp, Lp, B, Hkv, len)
    stage_x,  # (pp, G, 1, D)
    stage_pos,  # (pp, G) int32 — this token's write position
    stage_gstart,  # (pp,) int32 — first slot of the group each stage holds
    rolled: bool = False,
):
    """One pipelined microtick: every stage runs its layer block on the
    group it holds. Returns (outputs (pp, G, 1, D), cache_st). With
    int8 stacks the per-layer scales thread into _block exactly as the
    unpipelined quant scan does, so quantize-at-write stays per-row
    identical. rolled=True threads ring-buffer semantics (position p
    writes slot p mod ring); the drain-tail and warmup stale writes
    land one position AHEAD of the final lengths, whose ring slot
    aliases a position already outside every attention window (ring
    >= window + slack), so the dense self-healing argument holds on
    the ring too."""
    G = stage_x.shape[1]
    quant = len(cache_st) == 4
    pattern = cfg.attn_pattern

    def one_stage(sp, blocks, x, pos, gstart):
        slices = tuple(
            jax.lax.dynamic_slice_in_dim(b, gstart, G, axis=1)
            for b in blocks
        )
        positions = pos[:, None]
        cos, sin = rope_angles(
            positions, cfg.rope_dim, cfg.rope_theta,
            yarn=cfg.rope_yarn, llama3=cfg.rope_llama3,
            linear=cfg.rope_linear,
        )
        if cfg.rope_local_theta is not None:
            # Dual rope (Gemma-3): window layers use the local theta.
            cos_l, sin_l = rope_angles(
                positions, cfg.rope_dim, cfg.rope_local_theta
            )
        else:
            cos_l = sin_l = None

        def run_one(xx, lp, vals, kind):
            local = cos_l is not None and kind == "window"
            xx, nc, _ = _block(
                cfg, mesh, attn_impl, xx, lp,
                cos_l if local else cos, sin_l if local else sin,
                cache=(vals[0], vals[1], pos, positions),
                kv_scales=(vals[2], vals[3]) if quant else None,
                attn_kind=kind, rolled=rolled,
            )
            return xx, nc

        if pattern is None:
            def body(xx, layer_in):
                return run_one(xx, layer_in[0], layer_in[1:], None)

            x, news = jax.lax.scan(body, x, (sp,) + slices)
        else:
            # Patterned stacks (Gemma-2/3, GPT-OSS over DENSE caches):
            # each stage's layer chunk starts at pattern phase 0
            # (validate_pp_pipeline enforces Lp % period == 0), so the
            # SHARED period walk (transformer.pattern_period_scan)
            # applies to the stage chunk exactly as it does to the
            # full stack.
            x, news = pattern_period_scan(pattern, x, sp, slices,
                                          run_one)
        blocks = tuple(
            jax.lax.dynamic_update_slice_in_dim(b, n, gstart, axis=1)
            for b, n in zip(blocks, news)
        )
        return x, blocks

    return jax.vmap(one_stage)(
        stage_params, cache_st, stage_x, stage_pos, stage_gstart
    )


def constrain_register(x, mesh):
    return constrain(x, mesh, _REG_AXES)


def validate_pp_pipeline(cfg: ModelConfig, mesh, n_slots: int,
                         kv_quant: Optional[str], rolling: bool,
                         swaps_cache: bool) -> int:
    """Checks the pp_pipeline=True configuration; returns pp."""
    from shellac_tpu.models.transformer import first_k_layout, grouped_moe

    if mesh is None or dict(mesh.shape).get("pp", 1) < 2:
        raise ValueError(
            "pp_pipeline needs a mesh with pp >= 2 (token-level "
            "pipelining staggers slot groups across pipeline stages)"
        )
    pp = dict(mesh.shape)["pp"]
    if swaps_cache:
        raise ValueError(
            "pp_pipeline is a dense-cache feature; the paged engine's "
            "block pools do not reshape into per-stage registers yet"
        )
    if first_k_layout(cfg) or grouped_moe(cfg):
        raise ValueError(
            "pp_pipeline needs a uniformly-stacked layer tree (no "
            "first_k_dense or moe_every layouts)"
        )
    if cfg.attn_pattern is not None and rolling:
        raise ValueError(
            "pp_pipeline on patterned models needs the DENSE cache: "
            "rolling_window would use the mixed ring/dense "
            "PatternedKVCache, whose per-kind stacks do not stage-"
            "split uniformly"
        )
    if n_slots % pp:
        raise ValueError(
            f"pp_pipeline needs n_slots divisible by pp: {n_slots} % "
            f"{pp} != 0 (slots split into pp staggered groups)"
        )
    if cfg.n_layers % pp:
        raise ValueError(
            f"pp_pipeline needs n_layers divisible by pp: "
            f"{cfg.n_layers} % {pp} != 0"
        )
    if cfg.attn_pattern is not None:
        period = len(cfg.attn_pattern)
        if (cfg.n_layers // pp) % period:
            raise ValueError(
                f"pp_pipeline on a patterned model needs each stage's "
                f"layer chunk to hold whole pattern periods: "
                f"(n_layers/pp)={cfg.n_layers // pp} % "
                f"period={period} != 0"
            )
    return pp
