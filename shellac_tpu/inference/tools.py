"""OpenAI tool calling over the byte-DFA constraint engine.

The subsystem that turns `tools` / `tool_choice` on a chat (or native)
request into a GRAMMAR, not a prayer: every tool call the model emits
is constrained token-by-token by the same schema->DFA compiler that
powers `response_format` (inference/constraints.py), so `arguments`
always parse as JSON and always validate against the declared
parameter schema — enforcement happens in the logit mask, not in a
retry loop.

Wire shape (the constrained model output):

    <tool_call>[{"name":"get_weather","arguments":{"city":"oslo"}}]

- A SENTINEL prefix marks the tool branch. `tool_choice: "required"`
  (or a named tool) compiles to `sentinel + calls-array` — the model
  CANNOT answer with free text. `"auto"` compiles to
  `(sentinel + calls-array | free-text)` where free-text is any
  output that does not start with the sentinel's first character:
  the model keeps its choice, but the instant it starts the sentinel
  it is committed to a well-formed call. `"none"` compiles nothing.
- The calls array is non-empty (`[call]` or `[call(,call)*]` with
  `parallel_tool_calls`), each call an anyOf over the declared tools:
  `{"name": <const>, "arguments": <declared parameter schema>}` in
  fixed property order — which is what makes incremental parsing
  trivial and exact.

Parsing back is a small character machine (`ToolCallStreamParser`)
shared by the non-streamed response, ndjson streaming, and SSE
streaming, so the streamed `arguments` fragments concatenate to
byte-identical JSON with the non-streamed result.
"""

from __future__ import annotations

import json
import re
import uuid
from typing import Any, Dict, List, Optional, Tuple

# Same escape set the constraint compiler uses for literals; the
# sentinel must pass through _Regex verbatim.
from shellac_tpu.inference.constraints import (
    _escape_literal as _escape_regex,
)
from shellac_tpu.inference.constraints import constraint_pattern

#: The tool-branch marker. Chosen printable-ASCII so every tokenizer's
#: byte surface covers it; '<' as the first character is what the
#: "auto" free-text branch excludes (see tool_grammar).
SENTINEL = "<tool_call>"

# Free text = anything NOT starting the sentinel (or nothing). Only
# the FIRST character is excluded — '<' later in the text is fine —
# so entering the sentinel is an explicit first-token decision.
_FREE_TEXT = r"([^<][\s\S]*)?"

# OpenAI function-name contract (letters, digits, _ . -, <= 64). Also
# what keeps the grammar and the stream parser simple: json.dumps of a
# valid name contains no escape sequences.
_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]{1,64}$")


class ToolContext:
    """Validated per-request tool state: the declared functions, the
    resolved choice mode, and the grammar pattern (None when
    `tool_choice: "none"` — tools are rendered into the prompt but
    the output is unconstrained and never parsed).

    `pattern` builds LAZILY on first access: the OpenAI facade parses
    the payload only to validate shapes and render the prompt, then
    the server parses it again to compile the constraint — per-tool
    schema lowering is the expensive half, and only the server's copy
    needs it. Schema errors therefore surface at pattern access; both
    call sites turn ValueError into a 400."""

    __slots__ = ("functions", "mode", "forced_name", "parallel",
                 "_pattern")

    def __init__(self, functions: List[dict], mode: str,
                 forced_name: Optional[str], parallel: bool):
        self.functions = functions
        self.mode = mode            # "auto" | "required" | "named" | "none"
        self.forced_name = forced_name
        self.parallel = parallel
        self._pattern: Optional[str] = None

    @property
    def pattern(self) -> Optional[str]:
        if self.mode == "none":
            return None
        if self._pattern is None:
            self._pattern = tool_grammar(
                self.functions, self.mode, self.forced_name,
                self.parallel,
            )
        return self._pattern


def _validate_functions(tools: Any) -> List[dict]:
    if not isinstance(tools, list) or not tools:
        raise ValueError('"tools" must be a non-empty list')
    out: List[dict] = []
    seen = set()
    for t in tools:
        if not isinstance(t, dict):
            raise ValueError(f"bad tool entry {t!r}")
        if t.get("type", "function") != "function":
            raise ValueError(
                f"tool type {t.get('type')!r} not supported (function)"
            )
        fn = t.get("function")
        if not isinstance(fn, dict) or "name" not in fn:
            raise ValueError(
                'each tool needs {"type": "function", "function": '
                '{"name": ...}}'
            )
        name = fn["name"]
        if not isinstance(name, str) or not _NAME_RE.match(name):
            raise ValueError(
                f"bad tool name {name!r} (letters, digits, _ . -, "
                "max 64 chars)"
            )
        if name in seen:
            raise ValueError(f"duplicate tool name {name!r}")
        seen.add(name)
        params = fn.get("parameters")
        if params is not None and not isinstance(params, dict):
            raise ValueError(
                f"tool {name!r}: parameters must be a JSON schema object"
            )
        out.append({
            "name": name,
            "description": fn.get("description") or "",
            "parameters": params,
        })
    return out


def _shift_local_refs(node: Any, prefix: str) -> Any:
    """Rewrite every local `$ref` (`#/...`) by `prefix` so a schema
    embedded at that location inside a synthesized wrapper document
    still resolves its references against ITS OWN root, per JSON
    Schema semantics — `#/$defs/x` in a tool's parameters must not be
    looked up in the wrapper."""
    if isinstance(node, dict):
        return {
            k: ("#" + prefix + v[1:]
                if k == "$ref" and isinstance(v, str)
                and v.startswith("#")
                else _shift_local_refs(v, prefix))
            for k, v in node.items()
        }
    if isinstance(node, list):
        return [_shift_local_refs(x, prefix) for x in node]
    return node


def _call_regex(fn: dict) -> str:
    """One call object `{"name": <const>, "arguments": <schema>}` as a
    regex, via the SAME schema->regex lowering `response_format` uses
    (fixed property order, $ref/format/additionalProperties rules and
    depth limit included — docs/structured_output.md)."""
    params = fn["parameters"]
    if params is None:
        # Undeclared parameters: any JSON object (depth-limited
        # generic grammar), the OpenAI default.
        params = {"type": "object"}
    # The parameters schema lands under /properties/arguments of the
    # wrapper document; its local refs must follow it there.
    params = _shift_local_refs(params, "/properties/arguments")
    return constraint_pattern({"json_schema": {
        "type": "object",
        "properties": {"name": {"const": fn["name"]},
                       "arguments": params},
        "required": ["name", "arguments"],
    }})


def tool_grammar(functions: List[dict], mode: str,
                 forced_name: Optional[str] = None,
                 parallel: bool = True) -> str:
    """The full output grammar for one request's tool configuration."""
    fns = functions
    if mode == "named":
        fns = [f for f in functions if f["name"] == forced_name]
    call = "(" + "|".join(_call_regex(f) for f in fns) + ")"
    arr = r"\[" + call + ("(," + call + ")*" if parallel else "") + r"\]"
    pat = _escape_regex(SENTINEL) + arr
    if mode == "auto":
        pat = "(" + pat + "|" + _FREE_TEXT + ")"
    return pat


def parse_payload_tools(payload: dict) -> Optional[ToolContext]:
    """Validate `tools` / `tool_choice` / `parallel_tool_calls` on a
    request payload. Returns None when the request declares no tools;
    raises ValueError (-> HTTP 400) on malformed shapes."""
    tools = payload.get("tools")
    choice = payload.get("tool_choice")
    if tools is None:
        if choice not in (None, "none"):
            raise ValueError("tool_choice needs a non-empty tools list")
        return None
    functions = _validate_functions(tools)
    parallel = payload.get("parallel_tool_calls")
    if parallel is None:
        parallel = True
    if not isinstance(parallel, bool):
        raise ValueError("parallel_tool_calls must be a boolean")
    forced = None
    if choice is None or choice == "auto":
        mode = "auto"
    elif choice == "none":
        mode = "none"
    elif choice == "required":
        mode = "required"
    elif isinstance(choice, dict):
        fn = choice.get("function")
        if (choice.get("type", "function") != "function"
                or not isinstance(fn, dict) or "name" not in fn):
            raise ValueError(
                'named tool_choice must be {"type": "function", '
                '"function": {"name": ...}}'
            )
        forced = fn["name"]
        if forced not in {f["name"] for f in functions}:
            raise ValueError(
                f"tool_choice names unknown tool {forced!r}"
            )
        mode = "named"
    else:
        raise ValueError(
            f"bad tool_choice {choice!r} "
            '(auto | none | required | {"type": "function", ...})'
        )
    return ToolContext(functions, mode, forced, parallel)


def tools_prompt_block(functions: List[dict]) -> str:
    """Deterministic tool-definition block rendered into the chat
    prompt (the fallback template injects it as a system turn; HF
    templates that accept `tools=` render their own)."""
    lines = [
        "# Tools",
        "You may call one or more of the functions below. To call "
        "functions, reply with",
        SENTINEL + '[{"name": <function-name>, '
        '"arguments": <arguments-object>}, ...]',
        "and nothing else. Available functions:",
    ]
    for f in functions:
        # No sort_keys: the schema must render in DECLARATION order —
        # the same property order the compiled grammar enforces — or
        # the prompt would steer the model against its own logit mask.
        lines.append(json.dumps(
            {"name": f["name"], "description": f["description"],
             "parameters": f["parameters"]},
            ensure_ascii=False,
        ))
    return "\n".join(lines)


def render_tool_calls(tool_calls: List[dict]) -> str:
    """An assistant history message's tool_calls rendered back into
    the SAME surface the model emits (multi-turn consistency: the
    model sees its own past calls in the format it produces)."""
    calls = []
    for tc in tool_calls:
        fn = tc.get("function") or {}
        args = fn.get("arguments", "{}")
        if isinstance(args, str):
            try:
                args = json.loads(args)
            except ValueError:
                raise ValueError(
                    f"assistant tool_calls arguments are not JSON: "
                    f"{args!r}"
                )
        calls.append({"name": fn.get("name", ""), "arguments": args})
    return SENTINEL + json.dumps(
        calls, ensure_ascii=False, separators=(",", ":")
    )


def _new_call_id() -> str:
    return "call_" + uuid.uuid4().hex[:24]


class ToolCallStreamParser:
    """Incremental scanner over the (constrained) model output.

    feed(text) takes the CUMULATIVE decoded output and returns the
    newly discovered events, each one of:

      ("content", str)                        — free-text delta
      ("tool_delta", {"index", "id"?, "type"?, "function": {...}})
                                              — OpenAI-shaped
                                                tool_calls delta item

    The first tool_delta of a call carries id/type/name and an empty
    arguments string; subsequent deltas carry raw `arguments`
    fragments that CONCATENATE to the exact JSON of the non-streamed
    result. Because the grammar fixes property order
    (`{"name": ..., "arguments": ...}`) and forbids whitespace, the
    machine is a strict expected-literal walk plus one depth-tracked
    value scan — no lookahead, no buffering beyond the current feed.

    The grammar guarantees well-formed input; anything that still
    diverges (an UNconstrained caller, a length-truncated tail) flips
    `broken` and stops emission — `result()` then returns None and
    the caller falls back to plain content.
    """

    def __init__(self, mode: str):
        self.mode = mode
        self.decided: Optional[str] = None  # None | "text" | "tool"
        self.broken = False
        self.calls: List[Dict[str, Any]] = []
        self._content_emitted = 0
        self._pos = 0                # chars consumed past the sentinel
        self._state = "array_start"
        self._expect = ""            # pending literal to match
        self._after = ""             # state after the literal matches
        self._depth = 0
        self._in_str = False
        self._esc = False

    # -- state helpers --

    def _expect_literal(self, lit: str, after: str) -> None:
        self._expect = lit
        self._after = after
        self._state = "literal"

    def _begin_call(self) -> None:
        self.calls.append({"id": _new_call_id(), "name": "",
                           "args": [], "done": False})
        self._expect_literal('"name":"', "name")

    def _flush_args(self, events: List[tuple], buf: List[str]) -> None:
        if buf:
            frag = "".join(buf)
            self.calls[-1]["args"].append(frag)
            events.append(("tool_delta", {
                "index": len(self.calls) - 1,
                "function": {"arguments": frag},
            }))
            buf.clear()

    # -- the machine --

    def feed(self, text: str) -> List[tuple]:
        events: List[tuple] = []
        if self.decided is None:
            if text.startswith(SENTINEL):
                self.decided = "tool"
            elif SENTINEL.startswith(text):
                return events  # still an ambiguous sentinel prefix
            else:
                self.decided = "text"
        if self.decided == "text":
            if len(text) > self._content_emitted:
                events.append(("content", text[self._content_emitted:]))
                self._content_emitted = len(text)
            return events
        payload = text[len(SENTINEL):]
        buf: List[str] = []
        for ch in payload[self._pos:]:
            if self.broken:
                break
            self._pos += 1
            st = self._state
            if st == "literal":
                if ch != self._expect[0]:
                    self.broken = True
                    break
                self._expect = self._expect[1:]
                if not self._expect:
                    self._state = self._after
            elif st == "array_start":
                if ch != "[":
                    self.broken = True
                    break
                self._state = "pre_call"
            elif st == "pre_call":
                if ch == "{":
                    self._begin_call()
                elif ch == "]" and self.calls:
                    self._state = "end"
                else:
                    self.broken = True
                    break
            elif st == "name":
                if ch == '"':
                    call = self.calls[-1]
                    events.append(("tool_delta", {
                        "index": len(self.calls) - 1,
                        "id": call["id"], "type": "function",
                        "function": {"name": call["name"],
                                     "arguments": ""},
                    }))
                    self._expect_literal(',"arguments":', "value")
                    self._depth = 0
                    self._in_str = False
                    self._esc = False
                else:
                    self.calls[-1]["name"] += ch
            elif st == "value":
                if self._in_str:
                    buf.append(ch)
                    if self._esc:
                        self._esc = False
                    elif ch == "\\":
                        self._esc = True
                    elif ch == '"':
                        self._in_str = False
                elif ch == "}" and self._depth == 0:
                    # The call object's closing brace, not part of the
                    # arguments value.
                    self._flush_args(events, buf)
                    self.calls[-1]["done"] = True
                    self._state = "post_call"
                else:
                    buf.append(ch)
                    if ch == '"':
                        self._in_str = True
                    elif ch in "{[":
                        self._depth += 1
                    elif ch in "}]":
                        self._depth -= 1
                        if self._depth < 0:
                            self.broken = True
                            break
            elif st == "post_call":
                if ch == ",":
                    self._state = "pre_call2"
                elif ch == "]":
                    self._state = "end"
                else:
                    self.broken = True
                    break
            elif st == "pre_call2":
                # After a comma only another call may follow.
                if ch == "{":
                    self._begin_call()
                else:
                    self.broken = True
                    break
            else:  # "end": the grammar allows nothing after ']'
                self.broken = True
                break
        # Mid-value chars scanned this feed are definitively part of
        # arguments — stream them now (result() falls back to None if
        # the call never completes, but a live stream must not buffer
        # a long arguments object until its closing brace).
        self._flush_args(events, buf)
        return events

    def result(self) -> Optional[List[dict]]:
        """The complete OpenAI tool_calls list — None unless the scan
        decided "tool" and reached a clean end of the calls array."""
        if (self.decided != "tool" or self.broken
                or self._state != "end" or not self.calls):
            return None
        return [
            {"id": c["id"], "type": "function",
             "function": {"name": c["name"],
                          "arguments": "".join(c["args"])}}
            for c in self.calls
        ]


def parse_tool_calls(text: str, mode: str
                     ) -> Tuple[Optional[str], Optional[List[dict]]]:
    """Non-streamed detection/parse of a finished output.

    Returns (content, tool_calls): exactly one is non-None. A
    length-truncated or out-of-grammar tool branch falls back to the
    RAW text as content (scope honesty: never fabricate a call)."""
    p = ToolCallStreamParser(mode)
    p.feed(text)
    calls = p.result()
    if calls is not None:
        return None, calls
    return text, None


def events_to_stream(events: List[tuple]) -> Optional[Dict[str, Any]]:
    """Collapse one feed()'s events into the `tool_stream` field a
    native streaming record carries: {"content": str?,
    "tool_calls": [delta, ...]?} — None when the feed produced
    nothing (the record then omits the field)."""
    content: List[str] = []
    deltas: List[dict] = []
    for kind, val in events:
        if kind == "content":
            content.append(val)
        else:
            deltas.append(val)
    out: Dict[str, Any] = {}
    if content:
        out["content"] = "".join(content)
    if deltas:
        out["tool_calls"] = deltas
    return out or None


def safe_stream_text(text: str) -> str:
    """Trim trailing replacement characters before feeding a CUMULATIVE
    decode to the parser: a byte-level tokenizer mid-way through a
    multi-byte UTF-8 character decodes the partial tail as U+FFFD, and
    the parser consumes each character exactly once — feeding it a
    placeholder that the next token retroactively changes would
    corrupt the scan. The final (complete) text is fed unconditionally
    at finish, so a legitimate trailing U+FFFD is only DELAYED."""
    return text.rstrip("�")
