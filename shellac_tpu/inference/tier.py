"""Multi-replica serving tier: a failure-aware router over N
`InferenceServer` replicas.

One engine process is a single point of failure: a wedge, a restart-
budget exhaustion, or a planned redeploy is a full outage for its
users. This tier turns N independent replicas (each its own process,
each already self-healing per docs/inference.md) into one service with
four pillars:

  membership & health — a poller hits every replica's `/health` (the
    PR 2 readiness signal: 200 only while serving) on an interval;
    503s, timeouts, and connect errors feed a per-replica
    `utils.failure.CircuitBreaker` (sliding-window trip), and a
    tripped replica is EJECTED from routing. After the breaker's
    cooldown the poller sends a single half-open probe and readmits
    the replica iff it answers healthy. An optional `replica_factory`
    replaces a replica that stays dead past `respawn_after` seconds —
    the supervisor's `engine_factory` pattern, one level up.

  failure-aware requests — retryable outcomes (connect error/reset,
    HTTP 503 + Retry-After, 429, a replica fault 500, and in-band
    stream errors marked `retryable` — all of which fire before any
    byte reached the client) are retried on a DIFFERENT replica with
    capped exponential backoff and full jitter, never sleeping past
    the request's absolute deadline. Non-retryable outcomes (4xx bad
    requests, mid-stream loss after bytes were forwarded) fail loudly
    — a retry would silently duplicate a partial completion.

  routing policy — each request derives an affinity key (explicit
    `session`, the OpenAI `user` field, or a hash of the prompt's
    token/text prefix); rendezvous hashing maps the key onto the
    routable replicas so a session keeps landing where its prefix KV
    lives. Affinity yields to load: replicas are scored from their
    live `/metrics` gauges (queue depth, pending, KV utilization, p99
    TTFT from the histogram buckets), and when the affinity target's
    score exceeds the least-loaded's by more than a tolerance — scaled
    by the estimated prefix-hit value, and discounted when the target
    reports no prefix-cache blocks to hit — the request spills to the
    least-loaded replica instead of queueing behind a hot spot.

  graceful drain — a replica put into drain (POST /drain, directly or
    through this router's /admin/drain) flips readiness and refuses
    admissions while completing in-flight work; the health poller
    observes the flip and bleeds traffic off, so the replica can exit
    after `pending` reaches zero with zero dropped requests.

HTTP surface (make_tier_http_server):
  POST /generate, /v1/completions, /v1/chat/completions — routed,
       streaming and non-streaming, same payloads as a replica.
  GET  /v1/models — forwarded from a routable replica.
  GET  /health — 200 iff at least one replica is routable.
  GET  /stats — per-replica state, load scores, breaker states.
  GET  /metrics — Prometheus exposition of the shellac_tier_* series
       (docs/observability.md; counters: routed/retried/ejected/
       readmitted/drained/respawned per replica), PLUS the federated
       block: every replica series re-exposed with a `replica` label
       (last-known-good through outages, staleness-stamped) and the
       tier-computed shellac_fleet_* aggregates.
  GET  /slo — burn rates, alert states, and objectives of the
       configured SLOs (404 when serve-tier ran without --slo).
  POST /admin/drain {"replica": url-or-index[, "resume": true]} —
       forward a drain to one replica and stop routing to it now.
"""

from __future__ import annotations

import concurrent.futures
import hashlib
import http.client
import json
import random
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Tuple

from shellac_tpu.obs import (
    REQUEST_ID_HEADER,
    TRACE_HEADER,
    EventSpool,
    FleetCollector,
    FlightRecorder,
    IncidentManager,
    Registry,
    SLOEngine,
    SLOSpec,
    TierMetrics,
    adopt_trace,
    cumulative_at,
    format_trace_header,
    get_registry,
    histogram_quantile,
    new_trace_id,
    parse_prometheus_text,
    parse_slo_specs,
    spool_path,
)
from shellac_tpu.inference import prefix as prefix_mod
from shellac_tpu.inference.autoscale import Autoscaler, AutoscalePolicy
from shellac_tpu.inference.fabric import PrefixDirectory
from shellac_tpu.inference.qos import (
    ANONYMOUS,
    TENANT_HEADER,
    AdmissionController,
    TenantPolicy,
)
from shellac_tpu.utils.failure import CircuitBreaker

#: Parsed-metrics keys the load score reads (PR 3 gauge names).
_QUEUE_GAUGES = ("shellac_engine_queue_depth", "shellac_pending_requests")
_KV_GAUGE = "shellac_kv_utilization"
_TTFT_HIST = "shellac_ttft_seconds"
_PREFIX_GAUGE = "shellac_prefix_cache_blocks"
#: Resident KV bytes per token (backend-reported): the KV-migration
#: transfer-cost estimate's scale factor.
_KVBPT_GAUGE = "shellac_engine_kv_bytes_per_token"


def parse_prometheus(text: str) -> Dict[str, Any]:
    """Legacy flat view over the shared `obs.parse_prometheus_text`
    parser: unlabeled samples map to floats; every histogram family
    maps to `{name}!buckets` -> cumulative (le, count) pairs, summed
    edge-wise across the family's label sets (the label-aware parser
    is what fixed labeled histograms — the old splitter interleaved
    e.g. the per-phase step-time series into one garbage bucket
    list). Kept for the scorer and tests; new code should use
    `parse_prometheus_text` directly."""
    parsed = parse_prometheus_text(text)
    out: Dict[str, Any] = {}
    families = set()
    for name, labels, value in parsed.samples:
        if name.endswith("_bucket") and "le" in labels:
            families.add(name[: -len("_bucket")])
        elif not labels:
            out[name] = value
    for fam in families:
        out[fam + "!buckets"] = parsed.buckets(fam)
    return out


class Replica:
    """Router-side record of one replica: URL, circuit breaker, last
    observed health state, and the load snapshot the picker scores.
    Mutated by the health poller and request threads under `lock`."""

    __slots__ = ("url", "breaker", "lock", "state", "load",
                 "last_ok", "added_at", "pending", "role")

    def __init__(self, url: str, breaker: CircuitBreaker):
        self.url = url.rstrip("/")
        self.breaker = breaker
        self.lock = threading.Lock()
        # "unknown" | "healthy" | "draining" | "ejected"
        self.state = "unknown"
        self.load: Dict[str, Any] = {}
        self.last_ok: Optional[float] = None
        self.added_at = time.monotonic()
        self.pending = 0  # from the last health poll
        # Disaggregated-serving role from /health ("prefill" |
        # "decode" | "monolith"); the pair scheduler groups by it.
        self.role = "monolith"

    @property
    def routable(self) -> bool:
        return self.state == "healthy"

    def snapshot(self) -> Dict[str, Any]:
        with self.lock:
            return {
                "url": self.url,
                "state": self.state,
                "role": self.role,
                "breaker": self.breaker.state,
                "pending": self.pending,
                "load_score": self.load.get("score"),
                "last_ok_age_s": (
                    None if self.last_ok is None
                    else round(time.monotonic() - self.last_ok, 3)
                ),
            }


class _Retryable(Exception):
    """One attempt failed in a way a DIFFERENT replica might serve:
    nothing reached the client, so re-issuing is safe."""

    def __init__(self, kind: str, msg: str, *, breaker: bool,
                 retry_after: Optional[float] = None):
        super().__init__(msg)
        self.kind = kind          # connect|timeout|status_503|status_429|
        #                           status_500|stream_pre_byte
        self.breaker = breaker    # should this failure feed the breaker?
        self.retry_after = retry_after


class _Permanent(Exception):
    """The replica answered definitively (4xx): relay, never retry."""

    def __init__(self, status: int, body: bytes, content_type: str):
        super().__init__(f"HTTP {status}")
        self.status = status
        self.body = body
        self.content_type = content_type


class TierRouter:
    def __init__(
        self,
        replicas: List[str],
        *,
        replica_factory: Optional[Callable[[str], str]] = None,
        health_interval: float = 0.5,
        health_timeout: float = 2.0,
        breaker_failures: int = 3,
        breaker_window: float = 30.0,
        breaker_cooldown: float = 5.0,
        respawn_after: Optional[float] = None,
        max_attempts: int = 4,
        backoff_base: float = 0.05,
        backoff_cap: float = 2.0,
        default_timeout: float = 60.0,
        affinity_tolerance: float = 4.0,
        registry: Optional[Registry] = None,
        metrics: bool = True,
        debug: bool = True,
        federate: bool = True,
        stale_after: float = 5.0,
        slos: Optional[List[Any]] = None,
        slo_page_burn: float = 14.4,
        slo_warn_burn: float = 1.0,
        disagg: bool = True,
        kv_bandwidth: float = 1e9,
        disagg_min_prompt: int = 64,
        disagg_attempts: int = 2,
        fabric: bool = True,
        fabric_hot_hits: int = 4,
        fabric_max_push: int = 2,
        spool_dir: Optional[str] = None,
        spool_max_bytes: int = 8 << 20,
        incident_dir: Optional[str] = None,
        incident_rate: int = 6,
        incident_window: float = 600.0,
        incident_retention: int = 24,
        tenant_config: Optional[Any] = None,
        autoscale: Optional[AutoscalePolicy] = None,
    ):
        if not replicas:
            raise ValueError("a tier needs at least one replica URL")
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if health_interval <= 0 or health_timeout <= 0:
            raise ValueError("health interval/timeout must be > 0")
        if registry is None:
            registry = get_registry() if metrics else Registry(enabled=False)
        self._registry = registry
        self._m = TierMetrics(registry)
        # Tier-side flight recorder: the per-request ATTEMPT log
        # (tier-attempt / retry / tier-finish under the request's trace
        # id) plus replica-scoped events (eject / readmit / severed).
        # The same trace id indexes the replica's own recorder, so one
        # id walks the whole path. debug=False 404s the tier's /debug
        # endpoints and stops recording (mirrors --no-metrics).
        self._debug = bool(debug)
        # Durable spool (serve-tier --spool-dir): the tier's attempt
        # log survives a router kill the same way a replica's does.
        # No text ever reaches the tier recorder, so include_text
        # stays False unconditionally.
        self._spool = (
            EventSpool(spool_path(spool_dir),
                       max_bytes=spool_max_bytes)
            if spool_dir and self._debug else None
        )
        self._recorder = FlightRecorder(registry=registry,
                                        enabled=self._debug,
                                        spool=self._spool)
        # Incident black box (serve-tier --incident-dir): SLO page
        # transitions, severed streams, exhausted retries, and failed
        # migrations each snapshot the tier's whole evidence surface —
        # including a federated fetch of every routable replica's
        # in-flight table and incident list — into one atomic bundle.
        self._incidents: Optional[IncidentManager] = None
        if incident_dir and self._debug:
            self._incidents = IncidentManager(
                incident_dir,
                source="tier",
                registry=registry,
                recorder=self._recorder,
                sections={
                    "flight_recorder": lambda: self._recorder.tail(
                        self._recorder.capacity),
                    "metrics": registry.snapshot,
                    "requests": self.debug_requests,
                    "slo": self.slo_status,
                    "replicas": self.health,
                    "fleet": self._fleet_evidence,
                },
                rate=incident_rate,
                rate_window=incident_window,
                retention=incident_retention,
            )
        # Metrics federation: the health poller's /metrics pull feeds
        # the collector, which re-exposes every replica series (with a
        # `replica` label, last-known-good through outages) plus the
        # shellac_fleet_* aggregates on THIS tier's /metrics.
        self._fleet: Optional[FleetCollector] = (
            FleetCollector(stale_after=stale_after) if federate else None
        )
        # SLO burn-rate engine over the federated counts + the tier's
        # own outcome/latency series; evaluated on the poll cadence.
        self._slo: Optional[SLOEngine] = None
        if slos:
            specs = [s if isinstance(s, SLOSpec) else SLOSpec.parse(s)
                     for s in slos]
            parse_slo_specs([s.name for s in specs])  # dup check
            self._slo = SLOEngine(
                specs, registry=registry, recorder=self._recorder,
                exemplar_fn=self._slo_exemplar,
                on_transition=self._slo_transitioned,
                page_burn=slo_page_burn, warn_burn=slo_warn_burn,
            )
        # Multi-tenant QoS at the tier edge (serve-tier
        # --tenant-config): the SAME policy language as the replicas,
        # enforced here first so an over-quota tenant's traffic never
        # even reaches a replica's queue. ValueError on a malformed
        # config fails startup loudly.
        self._tenant_policy: Optional[TenantPolicy] = (
            TenantPolicy.parse(tenant_config)
            if tenant_config is not None else None
        )
        self._admission: Optional[AdmissionController] = (
            AdmissionController(self._tenant_policy)
            if self._tenant_policy is not None else None
        )
        # SLO-actuated autoscaler (serve-tier --autoscale): pure
        # policy — its actuators are this router's replica_factory
        # (scale-out) and drain forwarding (scale-down), its inputs
        # the SLO transitions + the health sweep's load scores, its
        # cadence poll_once. None (the default) constructs NOTHING,
        # so an autoscale-less tier is bit-identical to one predating
        # the feature.
        self._autoscaler: Optional[Autoscaler] = None
        if autoscale is not None:
            self._autoscaler = Autoscaler(
                autoscale,
                scale_out=self._scale_out_replica,
                scale_down=self._scale_down_replica,
                observe=self._fleet_load,
                on_action=self._autoscale_acted,
            )
        self._t0 = time.monotonic()
        self.health_interval = health_interval
        self.health_timeout = health_timeout
        self.max_attempts = max_attempts
        self.backoff_base = backoff_base
        self.backoff_cap = backoff_cap
        self.default_timeout = default_timeout
        self.affinity_tolerance = affinity_tolerance
        self.respawn_after = respawn_after
        # Disaggregated prefill/decode routing: active only when the
        # fleet actually advertises roles (a pure-monolith fleet pays
        # nothing). kv_bandwidth (bytes/s) scales the transfer-cost
        # estimate; prompts shorter than disagg_min_prompt — or whose
        # estimated transfer cost exceeds the measured prefill
        # interference (the federated step-phase digests) — serve
        # monolithically; disagg_attempts bounds full-path re-runs
        # before the monolithic fallback.
        if kv_bandwidth <= 0:
            raise ValueError("kv_bandwidth must be > 0 bytes/s")
        if disagg_attempts < 1:
            raise ValueError("disagg_attempts must be >= 1")
        self.disagg = bool(disagg)
        self.kv_bandwidth = float(kv_bandwidth)
        self.disagg_min_prompt = int(disagg_min_prompt)
        self.disagg_attempts = int(disagg_attempts)
        # KV fabric: the prefix directory (delta-polled on the health
        # sweep) makes routing cache-contents-aware, and the
        # replication planner pushes chains hot above fabric_hot_hits
        # fleet-wide hits to routable peers that lack them — at most
        # fabric_max_push pushes per sweep, each gated by the same
        # transfer-vs-recompute cost rule as migration.
        if fabric_hot_hits < 1:
            raise ValueError("fabric_hot_hits must be >= 1")
        if fabric_max_push < 0:
            raise ValueError("fabric_max_push must be >= 0")
        self.fabric = bool(fabric)
        self.fabric_hot_hits = int(fabric_hot_hits)
        self.fabric_max_push = int(fabric_max_push)
        self._directory: Optional[PrefixDirectory] = (
            PrefixDirectory() if self.fabric else None
        )
        # (tip hex, target url) -> monotonic stamp of the last push
        # order, so a still-hot chain is not re-pushed every sweep
        # while the receiver's manifest catches up. Poller thread only.
        self._pushed: Dict[Tuple[str, str], float] = {}
        # Built eagerly with the poll pool (not at first push): every
        # worker thread the router owns starts at construction and
        # stops in close(), so nothing spawned mid-flight outlives the
        # router unnoticed.
        self._fabric_pool: Optional[
            concurrent.futures.ThreadPoolExecutor] = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=2,
                thread_name_prefix="shellac-fabric-push",
            ) if self.fabric else None
        )
        if self._fabric_pool is not None:
            # The executor spawns workers lazily on submit; force them
            # up front so the first hot chain does not pay a thread
            # spawn and the full worker set exists from construction.
            for _ in range(2):
                self._fabric_pool.submit(lambda: None)
        self._factory = replica_factory
        self._breaker_cfg = (breaker_failures, breaker_window,
                             breaker_cooldown)
        # Membership list: replaced wholesale under _lock on respawn;
        # readers grab the reference once (plain-list reads are
        # atomic) so a swap mid-request is benign.
        self._lock = threading.Lock()
        self._replicas: List[Replica] = [
            Replica(u, CircuitBreaker(*self._breaker_cfg))
            for u in replicas
        ]
        if len({r.url for r in self._replicas}) != len(self._replicas):
            raise ValueError("duplicate replica URLs")
        self._closed = threading.Event()
        # Reused pool for the concurrent health sweep: a thread per
        # replica per sweep would churn 2N threads/second forever.
        self._poll_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=min(32, len(self._replicas)),
            thread_name_prefix="shellac-tier-poll",
        )
        self._poller = threading.Thread(
            target=self._poll_loop, daemon=True, name="shellac-tier-health"
        )
        self._poller.start()

    # ---- membership & health ----------------------------------------

    @property
    def replicas(self) -> List[Replica]:
        return list(self._replicas)

    def _get(self, url: str, path: str,
             timeout: float) -> Tuple[int, bytes]:
        req = urllib.request.Request(url + path, method="GET")
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def _poll_loop(self) -> None:
        while not self._closed.wait(self.health_interval):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the poller must survive
                # A poll crash would silently freeze membership; keep
                # polling (individual replica errors are handled per
                # replica below, this catches router-side bugs).
                pass

    def poll_once(self) -> None:
        """One health sweep over every replica (the poller thread calls
        this on the interval; tests call it directly for determinism).
        Replicas are polled CONCURRENTLY: a sequential sweep would let
        one stalled replica (blocking its health GET to the timeout)
        delay ejections, readmissions, and drain observation for the
        whole fleet by N x health_timeout."""
        list(self._poll_pool.map(self._poll_replica, self._replicas))
        self._respawn_dead()
        healthy = sum(r.routable for r in self._replicas)
        self._m.healthy.set(healthy)
        for rep in self._replicas:
            self._m.replica_state.labels(replica=rep.url).set(
                1 if rep.routable else 0
            )
        if self._directory is not None:
            self._m.fabric_directory_chains.set(
                self._directory.distinct_blocks()
            )
            try:
                self._plan_replication()
            except Exception:  # noqa: BLE001 — replication is an
                # optimization; a planner bug must not stop health
                # sweeps from ejecting and readmitting replicas.
                pass
        if self._slo is not None:
            self._slo.tick(self._slo_counts())
        if self._autoscaler is not None:
            # Gauge tracks ROUTABLE capacity (what traffic can use),
            # not membership — a draining scale-down shows up here the
            # sweep it takes effect, not when the replica exits.
            self._m.autoscale_replicas.set(healthy)
            try:
                self._autoscaler.tick()
            except Exception:  # noqa: BLE001 — policy bugs must not
                pass           # stop health sweeps

    def _poll_replica(self, rep: Replica) -> None:
        with rep.lock:
            if rep.state == "ejected" and not rep.breaker.allow_probe():
                return  # still cooling down; skip the network round-trip
            probing = rep.state == "ejected"
        try:
            status, body = self._get(rep.url, "/health",
                                     self.health_timeout)
            health = json.loads(body or b"{}")
        except (OSError, ValueError, http.client.HTTPException):
            # HTTPException matters: a replica dying mid-health-body
            # raises IncompleteRead, and letting it escape here would
            # strand the breaker in half_open (probe never resolved) —
            # a permanent silent ejection.
            self._note_failure(rep, probing=probing)
            return
        if status == 200:
            with rep.lock:
                was = rep.state
                rep.breaker.record_success()
                rep.state = "healthy"
                rep.last_ok = time.monotonic()
                rep.pending = int(health.get("pending", 0))
                rep.role = str(health.get("role") or "monolith")
            if probing or was == "ejected":
                self._m.readmissions.labels(replica=rep.url).inc()
                self._recorder.record(None, "readmit", src="tier",
                                      replica=rep.url)
            self._scrape_load(rep)
            return
        if health.get("status") == "draining":
            # A drain is DELIBERATE: readiness is down but the replica
            # is healthy and completing work — bleed traffic off
            # without charging the breaker.
            with rep.lock:
                was = rep.state
                rep.breaker.record_success()
                rep.state = "draining"
                rep.last_ok = time.monotonic()
                rep.pending = int(health.get("pending", 0))
            if was != "draining":
                self._m.drains.labels(replica=rep.url).inc()
            # A draining replica still serves /metrics, and the bleed-
            # off is exactly when its numbers are interesting: keep the
            # federation fresh.
            self._scrape_load(rep)
            return
        self._note_failure(rep, probing=probing)

    def _note_failure(self, rep: Replica, probing: bool = False) -> None:
        del probing  # the breaker handles probe failures itself
        if self._fleet is not None:
            # The replica stopped answering: its federated series go
            # last-known-good (served with a rising staleness stamp)
            # rather than vanishing — a dying replica's final numbers
            # are the ones an incident review needs.
            self._fleet.mark_unreachable(rep.url)
        with rep.lock:
            tripped = rep.breaker.record_failure()
            newly = tripped and rep.state != "ejected"
            if tripped:
                rep.state = "ejected"
        if newly:
            self._m.ejections.labels(replica=rep.url).inc()
            # Replica-scoped recorder event (no trace id: an ejection
            # belongs to the fleet timeline, not one request).
            self._recorder.record(None, "eject", src="tier",
                                  replica=rep.url)

    def _scrape_load(self, rep: Replica) -> None:
        """Refresh the load snapshot from the replica's /metrics (the
        PR 3 gauges) and feed the SAME scrape to the federation
        collector — one pull, two consumers. A 404 (--no-metrics) or
        parse failure degrades to the health poll's pending count —
        routing still works, just on a coarser signal."""
        load: Dict[str, Any] = {}
        try:
            status, body = self._get(rep.url, "/metrics",
                                     self.health_timeout)
            if status == 200:
                text = body.decode()
                if self._fleet is not None:
                    parsed = self._fleet.observe(rep.url, text)
                else:
                    parsed = parse_prometheus_text(text)
                for k in _QUEUE_GAUGES + (_KV_GAUGE, _PREFIX_GAUGE,
                                          _KVBPT_GAUGE):
                    v = parsed.value(k)
                    if v is not None:
                        load[k] = v
                ttft = histogram_quantile(
                    parsed.buckets(_TTFT_HIST), 0.99
                )
                if ttft is not None:
                    load["ttft_p99"] = ttft
            elif self._fleet is not None:
                self._fleet.mark_unreachable(rep.url)
        except (OSError, ValueError, http.client.HTTPException):
            if self._fleet is not None:
                self._fleet.mark_unreachable(rep.url)
        if self._directory is not None:
            # Directory feed rides the same sweep: delta-polled (the
            # replica answers "unchanged" when its registry version
            # did not move), and best-effort — a missed poll costs one
            # sweep of staleness, which the directory tolerates by
            # design.
            try:
                status, body = self._get(
                    rep.url,
                    "/kv/prefixes?since="
                    f"{self._directory.since(rep.url)}",
                    self.health_timeout,
                )
                if status == 200:
                    self._directory.observe(
                        rep.url, json.loads(body or b"{}")
                    )
            except (OSError, ValueError, http.client.HTTPException):
                pass
        load["score"] = self._score(rep, load)
        with rep.lock:
            rep.load = load

    def _score(self, rep: Replica, load: Dict[str, Any]) -> float:
        """Scalar load: requests queued + pending ahead of a newcomer,
        a KV-pressure term (a near-full cache means imminent admission
        stalls), and a latency term so a replica that is slow for any
        unmodeled reason (noisy neighbor, thermal throttle) repels
        traffic too. Units are roughly 'requests in front of you'."""
        pending = load.get("shellac_pending_requests")
        if pending is None:
            pending = rep.pending
        queue = load.get("shellac_engine_queue_depth", 0.0)
        kv = load.get(_KV_GAUGE, 0.0)
        ttft = load.get("ttft_p99", 0.0)
        return float(pending) + float(queue) + 8.0 * float(kv) \
            + 2.0 * float(ttft)

    def _respawn_dead(self) -> None:
        if self._factory is None or self.respawn_after is None:
            return
        now = time.monotonic()
        for i, rep in enumerate(list(self._replicas)):
            ref = rep.last_ok if rep.last_ok is not None else rep.added_at
            if rep.state != "ejected" or now - ref < self.respawn_after:
                continue
            try:
                new_url = self._factory(rep.url)
            except Exception:  # noqa: BLE001 — factory faults must not
                continue      # kill the poller; retried next sweep
            with self._lock:
                if self._replicas[i] is rep:
                    self._replicas[i] = Replica(
                        new_url, CircuitBreaker(*self._breaker_cfg)
                    )
                    self._m.respawns.inc()
                    if self._fleet is not None:
                        # REPLACED, not merely down: the old replica's
                        # last-known-good series stop being served
                        # (the successor starts fresh ones).
                        self._fleet.forget(rep.url)
                    if self._directory is not None:
                        # The successor's cache starts cold — the
                        # predecessor's advertised contents must stop
                        # attracting traffic.
                        self._directory.forget(rep.url)

    # ---- autoscaler actuators ---------------------------------------

    def _fleet_load(self) -> Tuple[int, int, float]:
        """(routable, total, aggregate load score) — the autoscaler's
        observation. The per-replica score is the routing score the
        health sweep already computes (queue + pending + KV pressure +
        latency), so the autoscaler and the router agree on what
        'loaded' means by construction."""
        routable = 0
        load = 0.0
        reps = self._replicas
        for rep in reps:
            if not rep.routable:
                continue
            routable += 1
            with rep.lock:
                s = rep.load.get("score")
            load += float(s) if s is not None else float(rep.pending)
        return routable, len(reps), load

    def _scale_out_replica(self) -> Optional[str]:
        """Autoscaler scale-out actuator: mint one replica via
        replica_factory (seeded with a routable member's URL as the
        template, the same contract _respawn_dead uses) and append it
        to membership. Returns the new URL, or None when there is no
        factory or it produced a duplicate — the autoscaler counts
        that as a failed action and cools down."""
        if self._factory is None:
            return None
        reps = self._replicas
        template = next((r.url for r in reps if r.routable),
                        reps[0].url if reps else None)
        if template is None:
            return None
        new_url = self._factory(template)
        with self._lock:
            if any(r.url == new_url for r in self._replicas):
                return None
            # Replaced wholesale, never mutated (the membership
            # contract): readers hold a consistent snapshot.
            self._replicas = self._replicas + [  # shellac: ignore[SH010] — copy-on-write membership: the binding is replaced atomically under _lock (writer-writer serialization); lock-free readers snapshot the old or the new list, both consistent
                Replica(new_url, CircuitBreaker(*self._breaker_cfg))
            ]
        return new_url

    def _scale_down_replica(self) -> Optional[str]:
        """Autoscaler scale-down actuator: drain the least-loaded
        HEALTHY replica (graceful — it finishes in-flight work and
        parks its cache; PR 16's park/adopt recovers anything
        non-streaming it still holds). The autoscaler already
        enforced the min-replica floor before calling."""
        candidates = [r for r in self._replicas if r.state == "healthy"]
        if len(candidates) <= 1:
            # Never drain the last healthy member, whatever the
            # policy floor says — an all-draining fleet serves nobody.
            return None

        def score(rep: Replica) -> float:
            with rep.lock:
                s = rep.load.get("score")
            return float(s) if s is not None else float(rep.pending)

        victim = min(candidates, key=score)
        self.drain_replica(victim.url)  # OSError → autoscaler counts
        return victim.url               # the failure, cools down

    def _autoscale_acted(self, action: str, url: Optional[str],
                         **detail: Any) -> None:
        """Autoscaler evidence hook: every decision (actions AND
        refusals) is a fleet-timeline recorder event; actual capacity
        changes additionally bump the actions counter and freeze an
        incident bundle — a fleet that changed size is exactly the
        moment a reviewer wants the whole evidence surface."""
        self._recorder.record(None, "autoscale", src="tier",
                              action=action, replica=url, **detail)
        if action in ("scale_out", "scale_down"):
            self._m.autoscale_actions.labels(action=action).inc()
            self._incident("autoscale",
                           detail={"action": action, "replica": url,
                                   **detail})

    # ---- KV fabric: hot-prefix replication planner ------------------

    def _plan_replication(self) -> None:
        """One replication-planning pass (poller thread, after each
        sweep): chains whose fleet-wide hit count crossed
        fabric_hot_hits, held by a routable replica but absent on a
        routable supported peer, are ordered pushed holder → peer via
        POST /kv/push — a PLANNED movement schedule (TACCL's
        discipline), not whatever request order produces. Each push is
        gated by the migration cost rule: estimated transfer seconds
        (chain bytes / kv_bandwidth) must not exceed the recompute the
        replica-local hits of the last sweep would pay (hit delta ×
        measured prefill_dispatch phase cost). Unknowns lean toward
        pushing — the first digests arrive within a poll or two."""
        agg = self._directory.hot_chains()
        rows = sorted(
            ((tip, row) for tip, row in agg.items()
             if row["hits"] >= self.fabric_hot_hits),
            key=lambda kv: kv[1]["hits"], reverse=True,
        )
        if not rows:
            return
        now = time.monotonic()
        self._pushed = {k: t for k, t in self._pushed.items()
                        if now - t < 30.0}
        budget = self.fabric_max_push
        recompute = self._phase_mean_s("prefill_dispatch")
        for tip, row in rows:
            if budget <= 0:
                break
            routable = {r.url for r in self._replicas if r.routable}
            holders = [u for u in row["holders"] if u in routable]
            if not holders:
                continue
            holder = holders[0]
            targets = [
                r for r in self._replicas
                if r.routable
                and self._directory.supported(r.url)
                and not self._directory.holds(r.url, tip)
                and (tip, r.url) not in self._pushed
            ]
            if not targets:
                continue
            bs, depth = row["block_size"], row["depth"]
            if recompute is not None and recompute > 0 \
                    and bs > 0 and depth > 0:
                bpt = None
                for r in self._replicas:
                    if r.url == holder:
                        with r.lock:
                            v = r.load.get(_KVBPT_GAUGE)
                        if v:
                            bpt = float(v)
                if bpt:
                    transfer_s = (depth * bs * bpt
                                  / self.kv_bandwidth + 0.002)
                    saved_s = max(1, row["delta"]) * recompute
                    if transfer_s > saved_s:
                        self._m.fabric_pushes.labels(
                            outcome="skipped_cost").inc()
                        # Stamp the skip so a chain the cost rule
                        # rejects is not re-priced (and re-counted)
                        # every sweep while its hits stay flat.
                        for r in targets:
                            self._pushed[(tip, r.url)] = now
                        continue
            # Seed the least-loaded lacking peer first; one peer per
            # chain per sweep — the next sweep sees the updated
            # manifest and fans out further only if still hot.
            def score(r: Replica) -> float:
                with r.lock:
                    s = r.load.get("score")
                return s if s is not None else float(r.pending)

            target = min(targets, key=score)
            self._pushed[(tip, target.url)] = now
            budget -= 1
            self._fabric_pool.submit(
                self._fabric_push_leg, holder, tip, target.url
            )

    def _fabric_push_leg(self, holder: str, tip: str,
                         target: str) -> None:
        """Push worker: order `holder` to ship chain `tip` to
        `target`'s /kv/seed. Failures count and record — never raise:
        a lost push costs one more sweep of prefix misses, nothing
        else."""
        tid = new_trace_id()
        body = json.dumps({"chain": tip, "target": target}).encode()
        req = urllib.request.Request(
            holder + "/kv/push", data=body,
            headers={"Content-Type": "application/json",
                     TRACE_HEADER: format_trace_header(tid, 0)},
        )
        try:
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                out = json.loads(resp.read() or b"{}")
        except Exception as e:  # noqa: BLE001 — one best-effort leg
            self._m.fabric_pushes.labels(outcome="failed").inc()
            self._recorder.record(
                tid, "fabric-push", src="tier", holder=holder,
                target=target, chain=tip[:12],
                error=f"{type(e).__name__}: {e}",
            )
            return
        self._m.fabric_pushes.labels(outcome="ok").inc()
        self._recorder.record(
            tid, "fabric-push", src="tier", holder=holder,
            target=target, chain=tip[:12],
            seeded=out.get("seeded"), bytes=out.get("bytes"),
        )

    # ---- routing policy ---------------------------------------------

    @staticmethod
    def affinity_key(path: str, payload: dict) -> Tuple[Optional[str], int]:
        """(key, estimated shared-prefix tokens) for a request payload.

        Explicit `session` (native extension) or `user` (the OpenAI
        field) wins; otherwise the key hashes the prompt's leading
        tokens/characters, so prompts sharing a long prefix (few-shot
        headers, system prompts, agent scaffolds) co-locate on the
        replica whose prefix-cache block registry already holds that
        KV. The token estimate scales how much load imbalance an
        affinity hit is worth."""
        sess = payload.get("session") or payload.get("user")
        if sess:
            return f"s:{sess}", 256
        prefix: Any = None
        if payload.get("tokens") is not None:
            prefix = payload["tokens"]
        elif payload.get("prompt") is not None:
            prefix = payload["prompt"]
        elif payload.get("text") is not None:
            prefix = payload["text"]
        elif payload.get("messages"):
            first = payload["messages"][0]
            prefix = (first.get("content", "")
                      if isinstance(first, dict) else "")
        if prefix is None:
            return None, 0
        # The shared helper (inference.prefix) is the same one the
        # paged backend's chain hashes build on — routing and cache
        # contents key identically by construction.
        head, est = prefix_mod.affinity_head(prefix)
        return prefix_mod.affinity_hash(head), est

    @staticmethod
    def _rendezvous(key: str, url: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(f"{key}|{url}".encode(), digest_size=8)
            .digest(), "big",
        )

    def _pick(self, key: Optional[str], prefix_tokens: int,
              exclude: set, tokens: Optional[List[int]] = None
              ) -> Tuple[Optional[Replica], str]:
        """Choose a replica. The directory check runs FIRST: when the
        fabric directory has MEASURED that some candidate already
        holds this prompt's prefix KV (chain-hash overlap against its
        advertised block registry), that replica wins unless it is
        hotter than the least-loaded by more than the overlap-scaled
        tolerance — a measured hit needs no 4× discount. Otherwise the
        PR 6 heuristic: rendezvous affinity target unless it is
        ejected, draining, excluded (already failed this request), or
        hotter than the least-loaded by more than the hit-value-scaled
        tolerance — then least-loaded. Returns (None, reason) when
        nothing is routable."""
        routable = [r for r in self._replicas if r.routable]
        cands = [r for r in routable if r.url not in exclude]
        if not cands:
            # Every routable replica already failed this request once:
            # re-allow them rather than refusing outright (a replica
            # can recover between attempts; the backoff paces us).
            cands = routable
        if not cands:
            return None, "none"

        def score(r: Replica) -> float:
            with r.lock:
                s = r.load.get("score")
            return s if s is not None else float(r.pending)

        best = min(cands, key=score)
        if self._directory is not None and tokens:
            ovl = {r.url: self._directory.overlap(r.url, tokens)
                   for r in cands}
            dir_rep = max(cands,
                          key=lambda r: (ovl[r.url], -score(r)))
            o = ovl[dir_rep.url]
            if o > 0 and (score(dir_rep) - score(best)
                          <= self.affinity_tolerance
                          * min(1.0, o / 256.0)):
                self._m.fabric_directory_hits.inc()
                return dir_rep, "directory"
        if key is None:
            return best, "least_loaded"
        aff = max(cands, key=lambda r: self._rendezvous(key, r.url))
        if aff is best:
            return aff, "affinity"
        # Spill decision: how much queueing is this prefix hit worth?
        value = min(1.0, prefix_tokens / 256.0)
        with aff.lock:
            has_cache = aff.load.get(_PREFIX_GAUGE, 0.0) > 0
        if not has_cache:
            # No registered prefix blocks to hit: affinity is only
            # session stickiness, worth far less queueing.
            value *= 0.25
        if score(aff) - score(best) <= self.affinity_tolerance * value:
            return aff, "affinity"
        return best, "least_loaded"

    # ---- failure-aware request handling -----------------------------

    def _classify_http_error(self, rep: Replica,
                             e: urllib.error.HTTPError) -> Exception:
        body = e.read()
        ct = e.headers.get("Content-Type", "application/json")
        ra = e.headers.get("Retry-After")
        ra = float(ra) if ra and ra.replace(".", "", 1).isdigit() else None
        if e.code == 503:
            draining = b"draining" in body
            if draining:
                # Don't wait for the next poll to observe the flip.
                with rep.lock:
                    was = rep.state
                    if rep.state == "healthy":
                        rep.state = "draining"
                if was == "healthy":
                    self._m.drains.labels(replica=rep.url).inc()
            return _Retryable("status_503", body.decode(errors="replace"),
                              breaker=not draining, retry_after=ra)
        if e.code == 429:
            # Overload is backpressure, not breakage: retry elsewhere
            # without charging the breaker.
            return _Retryable("status_429", body.decode(errors="replace"),
                              breaker=False, retry_after=ra)
        if e.code >= 500:
            return _Retryable("status_500", body.decode(errors="replace"),
                              breaker=True)
        return _Permanent(e.code, body, ct)

    def _post(self, rep: Replica, path: str, payload: dict,
              timeout: float, trace_id: Optional[str] = None,
              attempt: int = 0):
        """One POST attempt; returns the open response (caller reads).
        Raises _Retryable/_Permanent with the failure classified. The
        request's trace id + THIS attempt's number ride the
        x-shellac-trace header, so the replica's span, its flight
        recorder, and the tier's attempt log all quote one id — and a
        replica can tell a first attempt from a retry leg."""
        tenant = payload.pop("_tenant", None)
        data = json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"}
        if trace_id is not None:
            headers[TRACE_HEADER] = format_trace_header(trace_id, attempt)
        if tenant:
            # The tenant id rides EVERY attempt (retry legs, disagg
            # prefill/adopt legs) the way the trace id does, so the
            # replica's per-tenant accounting and debug rows stay
            # correct whichever attempt lands. It travels as the
            # header, never in the replica-bound JSON body.
            headers[TENANT_HEADER] = str(tenant)
        req = urllib.request.Request(
            rep.url + path, data=data, headers=headers,
        )
        try:
            return urllib.request.urlopen(req, timeout=timeout)
        except urllib.error.HTTPError as e:
            raise self._classify_http_error(rep, e) from e
        except socket.timeout as e:
            raise _Retryable("timeout", f"replica timed out: {e}",
                             breaker=True) from e
        except urllib.error.URLError as e:
            if isinstance(getattr(e, "reason", None), socket.timeout):
                raise _Retryable("timeout", f"replica timed out: {e}",
                                 breaker=True) from e
            raise _Retryable("connect", f"replica unreachable: {e.reason}",
                             breaker=True) from e
        except (ConnectionError, OSError) as e:
            raise _Retryable("connect", f"replica connection failed: {e}",
                             breaker=True) from e

    def _attempt_failed(self, rep: Replica, e: _Retryable,
                        trace_id: Optional[str] = None,
                        attempt: int = 0) -> None:
        """Account one retryable attempt failure: the retries counter,
        the breaker (when the class charges it), and the request's
        flight-recorder retry leg — recorded HERE so a failure path
        can never charge the metrics without the timeline noticing."""
        self._m.retries.labels(replica=rep.url, kind=e.kind).inc()
        if e.breaker:
            self._note_failure(rep)
        if trace_id is not None:
            self._recorder.record(trace_id, "retry", src="tier",
                                  replica=rep.url, kind=e.kind,
                                  attempt=attempt)

    def _backoff(self, attempt: int, remaining: float) -> Optional[float]:
        """Full-jitter capped exponential backoff, bounded by the
        request's remaining deadline budget. None = no time left."""
        ceiling = min(self.backoff_cap,
                      self.backoff_base * (2.0 ** attempt))
        delay = random.uniform(0.0, ceiling)
        # Leave at least a sliver of budget for the attempt itself.
        if delay >= remaining - 0.01:
            return None
        return delay

    def _deadline(self, payload: dict) -> float:
        timeout = float(payload.get("timeout") or self.default_timeout)
        return time.monotonic() + timeout

    def _route_attempts(self, path: str, payload: dict,
                        deadline: float, stop: dict):
        """Generator of (replica, reason, remaining, attempt_payload,
        attempt): the shared retry loop. Callers `throw`-free: they
        report each
        failure via _attempt_failed and ask for the next attempt by
        iterating; the generator sleeps the backoff between attempts
        and stops when attempts or the deadline run out — recording
        WHICH in stop["why"] ("deadline" | "attempts"), because the
        caller cannot infer it from the clock: a backoff that no
        longer fits the remaining budget ends the loop with up to
        backoff_cap seconds still on it."""
        key, prefix_tokens = self.affinity_key(path, payload)
        # Token payloads get the directory's measured-overlap routing;
        # text payloads fall back to the affinity heuristic (chain
        # hashes are defined over token ids — the tier has no
        # tokenizer, so it cannot hash what it cannot tokenize).
        tokens = (payload.get("tokens")
                  if isinstance(payload.get("tokens"), list) else None)
        tried: set = set()
        stop["why"] = "attempts"
        # Attempt legs actually SENT — distinct from the loop index,
        # which also advances while waiting out an unroutable fleet:
        # the wire contract says attempt=0 is the first real leg.
        legs = 0
        for attempt in range(self.max_attempts):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                stop["why"] = "deadline"
                return
            if attempt > 0:
                delay = self._backoff(attempt - 1, remaining)
                if delay is None:
                    stop["why"] = "deadline"
                    return
                self._m.backoff.observe(delay)
                time.sleep(delay)
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    stop["why"] = "deadline"
                    return
            rep, reason = self._pick(key, prefix_tokens, tried,
                                     tokens=tokens)
            if rep is not None and legs > 0:
                # Relabel so the routed series distinguishes retry
                # traffic from first attempts (the reason the metric
                # documents); the failure class lives in the separate
                # retries counter.
                reason = "retry"
            if rep is None:
                # Nothing routable right now; wait out a poll interval
                # within the deadline in case a probe readmits someone.
                time.sleep(min(self.health_interval,
                               max(remaining - 0.01, 0.0)))
                continue
            tried.add(rep.url)
            # The replica sheds on ITS deadline too: hand it the
            # remaining budget so tier and replica agree on when this
            # request stops being worth prefilling.
            att = dict(payload)
            att["timeout"] = remaining
            att.pop("session", None)  # tier-level extension, not a
            #                           replica sampling knob
            yield rep, reason, remaining, att, legs
            legs += 1

    # ---- disaggregated prefill/decode routing -----------------------

    @staticmethod
    def _admission_cost(payload: dict) -> int:
        """Token-bucket cost of one request at the tier edge: the
        prompt-size estimate plus the decode budget. The tier has no
        tokenizer, so this is deliberately the same coarse estimate
        routing uses — the replica's own admission re-prices exactly
        on adoption."""
        mx = payload.get("max_tokens")
        if mx is None:
            mx = payload.get("max_new_tokens")
        try:
            mx = int(mx)
        except (TypeError, ValueError):
            mx = 16
        return TierRouter._prompt_tokens_est(payload) + max(mx, 1)

    @staticmethod
    def _prompt_tokens_est(payload: dict) -> int:
        """Prompt-size estimate for the transfer-cost model (exact for
        token payloads, the ~4 chars/token heuristic otherwise)."""
        if isinstance(payload.get("tokens"), list):
            return len(payload["tokens"])
        text = payload.get("text") or payload.get("prompt")
        if isinstance(text, str):
            return max(1, len(text) // 4)
        return 0

    def _phase_mean_s(self, phase: str) -> Optional[float]:
        """Fleet-mean seconds one engine step spends in `phase`, from
        the federated shellac_step_phase_seconds digests (PR 11). For
        phase="prefill_dispatch" this is the measured interference a
        co-located prefill inflicts on decode windows — the quantity
        the migration decision compares transfer cost against. None
        until the fleet has digests."""
        if self._fleet is None:
            return None
        tot_s = tot_c = 0.0
        for url in self._fleet.replicas():
            parsed = self._fleet.parsed(url)
            if parsed is None:
                continue
            s = parsed.value("shellac_step_phase_seconds_sum",
                             phase=phase)
            c = parsed.value("shellac_step_phase_seconds_count",
                             phase=phase)
            if s is not None and c:
                tot_s += s
                tot_c += c
        return (tot_s / tot_c) if tot_c else None

    def _roles_present(self) -> bool:
        return any(r.role in ("prefill", "decode")
                   for r in self._replicas)

    def _disagg_pair(self, ex_pre: set,
                     ex_dec: set) -> Optional[Tuple[Replica, Replica]]:
        """Least-loaded (prefill, decode) pair, soft-excluding
        replicas that already failed this request (re-allowed when the
        exclusion would empty a role — a replica can recover between
        attempts, like _pick's exclusion)."""

        def pick(role: str, exclude: set) -> Optional[Replica]:
            pool = [r for r in self._replicas
                    if r.routable and r.role == role]
            cands = [r for r in pool if r.url not in exclude] or pool
            if not cands:
                return None

            def score(r: Replica) -> float:
                with r.lock:
                    s = r.load.get("score")
                return s if s is not None else float(r.pending)

            return min(cands, key=score)

        pre = pick("prefill", ex_pre)
        dec = pick("decode", ex_dec)
        if pre is None or dec is None:
            return None
        return pre, dec

    def _disagg_fallback(self, tid: Optional[str], reason: str,
                         **fields) -> None:
        self._m.migrations.labels(outcome=f"fallback_{reason}").inc()
        self._recorder.record(tid, "migrate-fallback", src="tier",
                              reason=reason, **fields)

    def _disagg_applicable(self, payload: dict,
                           tid: Optional[str]) -> bool:
        """Should this request take the disaggregated path? False
        falls back to monolithic routing — counting WHY, unless the
        fleet has no roles at all (then disagg is simply inert)."""
        if not self.disagg or not self._roles_present():
            return False
        for key in ("num_beams", "tools", "constraint", "adopt",
                    "prefill_only", "echo"):
            if payload.get(key):
                self._disagg_fallback(tid, "feature", key=key)
                return False
        try:
            n = int(payload.get("n", 1) or 1)
            best_of = int(payload.get("best_of", n) or n)
        except (TypeError, ValueError):
            return False  # the replica will 400 it monolithically
        if n != 1 or best_of != 1:
            self._disagg_fallback(tid, "feature", key="n/best_of")
            return False
        est = self._prompt_tokens_est(payload)
        if est < self.disagg_min_prompt:
            self._disagg_fallback(tid, "cost", prompt_tokens=est)
            return False
        # Transfer-cost vs measured interference: migrate only when
        # shipping the prompt KV costs less than the decode-window
        # stall a co-located prefill measurably causes. Unknowns lean
        # toward migrating — the operator split the fleet by role on
        # purpose, and the first digests arrive within a poll or two.
        interference = self._phase_mean_s("prefill_dispatch")
        if interference is not None and interference > 0:
            bpt = None
            for r in self._replicas:
                if r.role == "prefill" and r.routable:
                    with r.lock:
                        v = r.load.get(_KVBPT_GAUGE)
                    if v:
                        bpt = max(bpt or 0.0, float(v))
            if bpt:
                transfer_s = est * bpt / self.kv_bandwidth + 0.002
                if transfer_s > interference:
                    self._disagg_fallback(
                        tid, "cost", prompt_tokens=est,
                        transfer_s=round(transfer_s, 6),
                        interference_s=round(interference, 6),
                    )
                    return False
        return True

    def _migrate_leg(self, pre: Replica, dec: Replica, path: str,
                     payload: dict, tid: str, remaining: float,
                     leg: int) -> str:
        """Leg 1 of the disaggregated path: prefill_only on `pre`,
        pushing KV to `dec`. Returns the migration id. Raises
        _Retryable (push failures carry the kv-push-failed marker so
        the caller excludes the DECODE side and spares the prefill
        replica's breaker) or _Permanent (the replica refused the
        payload — serve it monolithically for the honest 4xx)."""
        att = {k: v for k, v in payload.items()
               if k not in ("stream", "session")}
        att["prefill_only"] = True
        att["migrate_to"] = dec.url
        att["timeout"] = remaining
        self._m.routed.labels(replica=pre.url,
                              reason="disagg_prefill").inc()
        self._recorder.record(tid, "tier-attempt", src="tier",
                              replica=pre.url, reason="disagg_prefill",
                              attempt=leg, decode=dec.url)
        try:
            with self._post(pre, path, att, remaining, trace_id=tid,
                            attempt=leg) as resp:
                body = resp.read()
        except _Retryable as e:
            if "kv-push-failed" in str(e):
                # The prefill ran fine; DELIVERY to the decode replica
                # failed. Don't charge the prefill replica's breaker
                # for its partner's death.
                e.breaker = False
                e.kind = "kv_push"
            raise
        except (OSError, http.client.HTTPException) as e:
            raise _Retryable("connect",
                             f"prefill replica died mid-ack: {e}",
                             breaker=True) from e
        try:
            mig = json.loads(body)
            mid = mig["migration_id"]
        except (ValueError, KeyError) as e:
            raise _Retryable(
                "kv_push", f"malformed migration ack: {e}",
                breaker=False,
            ) from e
        return str(mid)

    def _disagg_attempts(self, path: str, payload: dict, tid: str,
                         deadline: float, state: dict,
                         stream: bool = False):
        """The disaggregated path's shared attempt loop — pair
        picking, the prefill+migrate leg, exclusion bookkeeping —
        yielding (dec, adopt_payload, remaining, attempt) once leg 1
        succeeded; the caller runs leg 2 (adopt) and, on a pre-byte
        decode failure, records it in `state` and keeps iterating to
        re-run the FULL path on a fresh pair (the retry contract).
        Mirrors how forward_json/open_stream share _route_attempts, so
        the two disagg surfaces cannot drift. `state` carries ex_pre/
        ex_dec (mutated by both sides), `last` (last failure), and
        `why` (fallback classification for _disagg_gave_up)."""
        for attempt in range(self.disagg_attempts):
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            pair = self._disagg_pair(state["ex_pre"], state["ex_dec"])
            if pair is None:
                if attempt == 0:
                    state["why"] = "no_pair"
                return
            pre, dec = pair
            try:
                mid = self._migrate_leg(pre, dec, path, payload, tid,
                                        remaining, attempt)
            except _Permanent:
                # The replica refused the payload for the disagg
                # protocol (4xx): serve it monolithically for the
                # honest answer instead of relaying a protocol leg's
                # refusal.
                state["why"] = "feature"
                state["replica"] = pre.url
                return
            except _Retryable as e:
                if e.kind == "kv_push":
                    state["ex_dec"].add(dec.url)
                else:
                    state["ex_pre"].add(pre.url)
                self._attempt_failed(pre, e, tid, attempt)
                state["last"] = e
                continue
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            adopt = {k: v for k, v in payload.items()
                     if k not in ("prefill_only", "migrate_to",
                                  "session")}
            adopt["adopt"] = mid
            adopt["timeout"] = remaining
            self._m.routed.labels(replica=dec.url,
                                  reason="disagg_decode").inc()
            self._recorder.record(tid, "tier-attempt", src="tier",
                                  replica=dec.url,
                                  reason="disagg_decode",
                                  attempt=attempt, stream=stream)
            yield dec, adopt, remaining, attempt

    def _adopt_failed(self, dec: Replica, e: _Retryable, tid: str,
                      attempt: int, state: dict) -> None:
        """Account one failed adopt leg (strictly pre-byte): the
        decode replica is excluded and the caller's next iteration
        re-runs the full prefill->migrate path on a fresh pair."""
        self._attempt_failed(dec, e, tid, attempt)
        state["ex_dec"].add(dec.url)
        state["last"] = e

    def _disagg_gave_up(self, tid: str, state: dict) -> None:
        """Classify + count why the disaggregated path stepped aside;
        the caller then serves monolithically (returns None)."""
        why = state.get("why")
        if why == "no_pair":
            self._disagg_fallback(tid, "no_pair")
        elif why == "feature":
            self._disagg_fallback(tid, "feature",
                                  replica=state.get("replica"))
        else:
            last = state.get("last")
            self._disagg_fallback(tid, "failed",
                                  last=str(last) if last else None)
            # A migration that FAILED mid-path (vs stepping aside for
            # a known reason) is incident-grade: the monolithic
            # fallback saves the request, the bundle saves the why.
            self._incident(
                "migration-failed", trace_id=tid,
                detail={"last": str(last) if last else None,
                        "excluded_prefill": sorted(state["ex_pre"]),
                        "excluded_decode": sorted(state["ex_dec"])},
            )

    @staticmethod
    def _disagg_state() -> dict:
        return {"ex_pre": set(), "ex_dec": set(), "last": None,
                "why": None, "replica": None}

    def _disagg_forward(self, path: str, payload: dict, tid: str,
                        deadline: float, t0: float
                        ) -> Optional[Tuple[int, bytes, str]]:
        """The disaggregated non-streaming path: (prefill+migrate,
        adopt) legs with full-path re-runs on a fresh pair when either
        leg fails strictly before the first client byte. Returns the
        response to relay, or None to serve monolithically (the
        fallback is counted)."""
        if not self._disagg_applicable(payload, tid):
            return None
        state = self._disagg_state()
        for dec, adopt, remaining, attempt in self._disagg_attempts(
                path, payload, tid, deadline, state):
            a0 = time.monotonic()
            try:
                with self._post(dec, path, adopt, remaining,
                                trace_id=tid, attempt=attempt) as resp:
                    try:
                        body = resp.read()
                    except (OSError,
                            http.client.HTTPException) as e:
                        raise _Retryable(
                            "connect",
                            f"decode replica died mid-response: {e}",
                            breaker=True,
                        ) from e
                    ct = resp.headers.get("Content-Type",
                                          "application/json")
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._m.outcomes.labels(outcome="ok").inc()
                self._m.migrations.labels(outcome="ok").inc()
                self._m.e2e.observe(time.monotonic() - t0,
                                    exemplar=tid)
                self._recorder.record(tid, "tier-finish", src="tier",
                                      replica=dec.url,
                                      status=resp.status,
                                      attempts=attempt + 1,
                                      migrated=True)
                return resp.status, body, ct
            except _Permanent:
                # A 4xx on the ADOPT leg is a protocol refusal the
                # client never asked for: serve the request
                # monolithically for the honest answer (same rule as
                # the streaming path — the two surfaces must agree).
                self._m.attempt_latency.observe(time.monotonic() - a0)
                state["why"] = "feature"
                state["replica"] = dec.url
                break
            except _Retryable as e:
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._adopt_failed(dec, e, tid, attempt, state)
                continue
        self._disagg_gave_up(tid, state)
        return None

    def _disagg_stream(self, path: str, payload: dict, tid: str,
                       deadline: float, t0: float):
        """The disaggregated streaming path: the same shared attempt
        loop, with the adopt leg's first event read BEFORE committing
        a 200 — so a decode death pre-byte re-runs the full path on a
        fresh pair, and a committed stream keeps the severed-stream
        contract. Returns open_stream's `opened` tuple, or None to
        serve monolithically."""
        if not self._disagg_applicable(payload, tid):
            return None
        state = self._disagg_state()
        sse = path.startswith("/v1/")
        for dec, adopt, remaining, attempt in self._disagg_attempts(
                path, payload, tid, deadline, state, stream=True):
            a0 = time.monotonic()
            try:
                resp = self._post(dec, path, adopt, remaining,
                                  trace_id=tid, attempt=attempt)
            except _Permanent:
                # Let monolithic routing give the client a live stream
                # instead of relaying a 4xx for a protocol leg it
                # never asked for.
                state["why"] = "feature"
                state["replica"] = dec.url
                break
            except _Retryable as e:
                self._adopt_failed(dec, e, tid, attempt, state)
                continue
            try:
                first = self._read_first_event(resp, sse)
            except (OSError, http.client.HTTPException) as e:
                resp.close()
                self._adopt_failed(
                    dec,
                    _Retryable("stream_pre_byte",
                               f"adopt stream died before first "
                               f"event: {e}", breaker=True),
                    tid, attempt, state,
                )
                continue
            if not first.strip():
                # Zero bytes then FIN: same breaker-charging class as
                # the monolithic open_stream's pre-byte close.
                resp.close()
                self._adopt_failed(
                    dec,
                    _Retryable("stream_pre_byte",
                               "adopt stream closed before first "
                               "event", breaker=True),
                    tid, attempt, state,
                )
                continue
            in_band = self._first_event_error(first, sse)
            if in_band is not None and in_band.get("retryable"):
                resp.close()
                self._adopt_failed(
                    dec,
                    _Retryable("stream_pre_byte",
                               str(in_band.get("message", "")),
                               breaker=False),
                    tid, attempt, state,
                )
                continue
            self._m.attempt_latency.observe(time.monotonic() - a0)
            self._m.outcomes.labels(outcome="ok").inc()
            self._m.migrations.labels(outcome="ok").inc()
            self._recorder.record(tid, "tier-finish", src="tier",
                                  replica=dec.url, status=200,
                                  attempts=attempt + 1, stream=True,
                                  migrated=True)
            ct = resp.headers.get("Content-Type",
                                  "text/event-stream" if sse
                                  else "application/x-ndjson")
            return resp, first, ct, dec.url, t0
        self._disagg_gave_up(tid, state)
        return None

    def forward_json(self, path: str, payload: dict,
                     trace_id: Optional[str] = None
                     ) -> Tuple[int, bytes, str]:
        """Route a non-streaming request. Returns (status, body bytes,
        content type) — always; failures come back as error responses,
        never exceptions. `trace_id` is the request's distributed
        trace id (minted here for programmatic callers); every attempt
        forwards it with its attempt number, and the tier's flight
        recorder logs the attempt/retry sequence under it."""
        t0 = time.monotonic()
        tid = trace_id or new_trace_id()
        deadline = self._deadline(payload)
        if self.disagg and path == "/generate":
            # Disaggregated path first; None falls through to the
            # monolithic routing below (the fallback rule).
            routed = self._disagg_forward(path, payload, tid,
                                          deadline, t0)
            if routed is not None:
                return routed
        stop: Dict[str, str] = {}
        last: Optional[_Retryable] = None
        for rep, reason, remaining, att, attempt in self._route_attempts(
                path, payload, deadline, stop):
            self._m.routed.labels(replica=rep.url, reason=reason).inc()
            self._recorder.record(tid, "tier-attempt", src="tier",
                                  replica=rep.url, reason=reason,
                                  attempt=attempt)
            a0 = time.monotonic()
            try:
                with self._post(rep, path, att, remaining,
                                trace_id=tid, attempt=attempt) as resp:
                    try:
                        body = resp.read()
                    except (OSError,
                            http.client.HTTPException) as e:
                        # Headers arrived but the body didn't (replica
                        # killed mid-response: IncompleteRead / reset).
                        # Nothing reached the client — retryable.
                        raise _Retryable(
                            "connect",
                            f"replica died mid-response: {e}",
                            breaker=True,
                        ) from e
                    ct = resp.headers.get("Content-Type",
                                          "application/json")
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._m.outcomes.labels(outcome="ok").inc()
                self._m.e2e.observe(time.monotonic() - t0, exemplar=tid)
                self._recorder.record(tid, "tier-finish", src="tier",
                                      replica=rep.url,
                                      status=resp.status,
                                      attempts=attempt + 1)
                return resp.status, body, ct
            except _Retryable as e:
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._attempt_failed(rep, e, tid, attempt)
                last = e
            except _Permanent as e:
                # A definitive replica answer (bad request): relay it
                # verbatim — the tier must not mask a 400 as transient.
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._m.outcomes.labels(outcome="failed").inc()
                self._m.e2e.observe(time.monotonic() - t0, exemplar=tid)
                self._recorder.record(tid, "tier-finish", src="tier",
                                      replica=rep.url, status=e.status,
                                      attempts=attempt + 1)
                return e.status, e.body, e.content_type
        return self._exhausted(t0, path, last, stop, tid)

    def _exhausted(self, t0: float, path: str,
                   last: Optional[_Retryable],
                   stop: dict,
                   trace_id: Optional[str] = None
                   ) -> Tuple[int, bytes, str]:
        """Classify a request that ran out of road: no replica was
        ever routable (503 rejected), the DEADLINE expired mid-retries
        (504), or the attempt budget drained with deadline to spare —
        an upstream availability problem, not client-deadline
        pressure, so 502 with outcome "failed" (a 504 here would read
        an outage as latency on every dashboard)."""
        if last is None:
            self._m.outcomes.labels(outcome="rejected").inc()
            msg = "no routable replica in the tier"
            status = 503
        elif stop.get("why") == "deadline":
            self._m.outcomes.labels(outcome="deadline").inc()
            msg = (f"deadline exhausted after retries; last failure: "
                   f"{last.kind}: {last}")
            status = 504
        else:
            self._m.outcomes.labels(outcome="failed").inc()
            msg = (f"replicas exhausted after {self.max_attempts} "
                   f"attempts; last failure: {last.kind}: {last}")
            status = 502
        self._m.e2e.observe(time.monotonic() - t0, exemplar=trace_id)
        self._recorder.record(trace_id, "tier-exhausted", src="tier",
                              status=status, why=stop.get("why"))
        # Exhaustion is the tier admitting it could not serve: bundle
        # the evidence (attempt log, breaker states, fleet snapshot).
        # The rate limiter keeps an outage from writing one bundle
        # per failed request.
        self._incident(
            "attempts-exhausted", trace_id=trace_id,
            detail={"status": status, "why": stop.get("why"),
                    "last": str(last) if last is not None else None},
        )
        if path.startswith("/v1/"):
            err: Dict[str, Any] = {"error": {"message": msg,
                                             "type": "overloaded_error"}}
            if trace_id is not None:
                err["error"]["trace_id"] = trace_id
        else:
            err = {"error": msg}
            if trace_id is not None:
                err["trace_id"] = trace_id
        return status, json.dumps(err).encode(), "application/json"

    # ---- streaming ---------------------------------------------------

    @staticmethod
    def _read_first_event(resp, sse: bool) -> bytes:
        """The stream's first client-visible unit: one ndjson line, or
        one SSE event (lines through the blank separator). Reading it
        BEFORE committing a 200 to the client is what makes pre-byte
        failures retryable."""
        if not sse:
            return resp.readline()
        lines = []
        while True:
            line = resp.readline()
            if not line:
                break
            lines.append(line)
            if line in (b"\n", b"\r\n"):
                break
        return b"".join(lines)

    @staticmethod
    def _first_event_error(first: bytes, sse: bool) -> Optional[dict]:
        """Parse an in-band error record out of the first event, if it
        is one (server.py emits {"error": {..., "retryable": ...}})."""
        data = first.strip()
        if sse:
            if not data.startswith(b"data: "):
                return None
            data = data[len(b"data: "):]
        try:
            obj = json.loads(data)
        except ValueError:
            return None
        if isinstance(obj, dict) and isinstance(obj.get("error"), dict):
            return obj["error"]
        return None

    def open_stream(self, path: str, payload: dict,
                    trace_id: Optional[str] = None):
        """Route a streaming request: retries attempts until one yields
        a healthy first event, then hands (response, first_event_bytes,
        content_type, replica_url, t0) to the HTTP layer to relay —
        the relay settles the e2e histogram when the stream actually
        ends, not here at the first event. On failure returns
        (None, (status, body, content_type)) — an ordinary error
        response, since nothing was committed to the client yet."""
        t0 = time.monotonic()
        tid = trace_id or new_trace_id()
        deadline = self._deadline(payload)
        if self.disagg and path == "/generate":
            opened = self._disagg_stream(path, payload, tid,
                                         deadline, t0)
            if opened is not None:
                return opened, None
        stop: Dict[str, str] = {}
        last: Optional[_Retryable] = None
        sse = path.startswith("/v1/")
        for rep, reason, remaining, att, attempt in self._route_attempts(
                path, payload, deadline, stop):
            self._m.routed.labels(replica=rep.url, reason=reason).inc()
            self._recorder.record(tid, "tier-attempt", src="tier",
                                  replica=rep.url, reason=reason,
                                  attempt=attempt, stream=True)
            a0 = time.monotonic()
            try:
                resp = self._post(rep, path, att, remaining,
                                  trace_id=tid, attempt=attempt)
            except _Retryable as e:
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._attempt_failed(rep, e, tid, attempt)
                last = e
                continue
            except _Permanent as e:
                self._m.attempt_latency.observe(time.monotonic() - a0)
                self._m.outcomes.labels(outcome="failed").inc()
                self._m.e2e.observe(time.monotonic() - t0, exemplar=tid)
                self._recorder.record(tid, "tier-finish", src="tier",
                                      replica=rep.url, status=e.status,
                                      attempts=attempt + 1)
                return None, (e.status, e.body, e.content_type)
            try:
                first = self._read_first_event(resp, sse)
            except (OSError, http.client.HTTPException) as e:
                resp.close()
                err = _Retryable("stream_pre_byte",
                                 f"stream died before first event: {e}",
                                 breaker=True)
                self._attempt_failed(rep, err, tid, attempt)
                last = err
                continue
            if not first.strip():
                # Clean FIN right after the upstream 200, zero bytes of
                # stream: nothing reached (or will reach) the client,
                # so this is a pre-byte failure — retry elsewhere, not
                # a committed-then-severed stream.
                resp.close()
                err = _Retryable("stream_pre_byte",
                                 "stream closed before first event",
                                 breaker=True)
                self._attempt_failed(rep, err, tid, attempt)
                last = err
                continue
            in_band = self._first_event_error(first, sse)
            if in_band is not None and in_band.get("retryable"):
                # The replica pushed back (shed/draining/recovering)
                # after the 200 was already committed upstream — but
                # NOTHING has reached our client, so retry elsewhere.
                resp.close()
                err = _Retryable("stream_pre_byte",
                                 str(in_band.get("message", "")),
                                 breaker=False)
                self._attempt_failed(rep, err, tid, attempt)
                last = err
                continue
            self._m.attempt_latency.observe(time.monotonic() - a0)
            self._m.outcomes.labels(outcome="ok").inc()
            self._recorder.record(tid, "tier-finish", src="tier",
                                  replica=rep.url, status=200,
                                  attempts=attempt + 1, stream=True)
            ct = resp.headers.get("Content-Type",
                                  "text/event-stream" if sse
                                  else "application/x-ndjson")
            return (resp, first, ct, rep.url, t0), None
        return None, self._exhausted(t0, path, last, stop, tid)

    # ---- admin / introspection --------------------------------------

    def drain_replica(self, which, resume: bool = False) -> dict:
        """Forward a drain (or resume) to one replica — `which` is its
        URL or list index — and update routing state immediately
        instead of waiting for the next health poll."""
        reps = self._replicas
        if isinstance(which, int) or (isinstance(which, str)
                                      and which.isdigit()):
            rep = reps[int(which)]
        else:
            matches = [r for r in reps
                       if r.url == str(which).rstrip("/")]
            if not matches:
                raise ValueError(f"unknown replica {which!r}")
            rep = matches[0]
        data = json.dumps({"resume": True} if resume else {}).encode()
        req = urllib.request.Request(
            rep.url + "/drain", data=data,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(
                req, timeout=self.health_timeout) as r:
            health = json.loads(r.read())
        with rep.lock:
            was = rep.state
            if resume:
                if rep.state == "draining":
                    rep.state = "healthy"
            elif rep.state == "healthy":
                rep.state = "draining"
        if not resume and was == "healthy":
            self._m.drains.labels(replica=rep.url).inc()
        return {"replica": rep.url, "state": rep.state, **health}

    def health(self) -> Dict[str, Any]:
        reps = [r.snapshot() for r in self._replicas]
        healthy = sum(1 for r in reps if r["state"] == "healthy")
        return {
            "status": "ok" if healthy else "unavailable",
            "ok": healthy > 0,
            "replicas_healthy": healthy,
            "replicas_total": len(reps),
            "replicas": reps,
        }

    def stats(self) -> Dict[str, Any]:
        reg = self._registry

        def total(name):
            return int(reg.total(name) or 0)

        return {
            **self.health(),
            "uptime_s": round(time.monotonic() - self._t0, 3),
            "routed": total("shellac_tier_routed_total"),
            "retried": total("shellac_tier_retries_total"),
            "ejected": total("shellac_tier_ejections_total"),
            "readmitted": total("shellac_tier_readmissions_total"),
            "drains_observed": total("shellac_tier_drains_observed_total"),
            "respawned": total("shellac_tier_respawns_total"),
            # Disaggregated serving: full paths served vs monolithic
            # fallbacks (by-reason splits live on /metrics).
            "migrated": int(reg.value("shellac_migrations_total",
                                      outcome="ok") or 0),
            "migrate_fallbacks": int(sum(
                reg.value("shellac_migrations_total",
                          outcome=f"fallback_{r}") or 0
                for r in ("no_pair", "cost", "feature", "failed")
            )),
            # Multi-tenant QoS: per-tenant admission counters (null
            # without --tenant-config) and autoscaler status (null
            # without --autoscale).
            "tenants": (self._admission.snapshot()
                        if self._admission is not None else None),
            "autoscale": (self._autoscaler.status()
                          if self._autoscaler is not None else None),
            # KV fabric: per-replica directory view + push/hit tallies
            # (null when serve-tier ran with --no-fabric).
            "fabric": None if self._directory is None else {
                "directory": self._directory.stats(),
                "directory_chains": self._directory.distinct_blocks(),
                "directory_hits": total(
                    "shellac_fabric_directory_hits_total"),
                "pushes_ok": int(reg.value(
                    "shellac_fabric_pushes_total", outcome="ok") or 0),
                "pushes_failed": int(reg.value(
                    "shellac_fabric_pushes_total",
                    outcome="failed") or 0),
                "pushes_skipped_cost": int(reg.value(
                    "shellac_fabric_pushes_total",
                    outcome="skipped_cost") or 0),
            },
        }

    # ---- SLO engine wiring ------------------------------------------

    def _slo_counts(self) -> Dict[str, Tuple[float, float]]:
        """Cumulative (good, total) event counts per configured SLO —
        the burn-rate engine's input, differenced per window there.

        Latency SLIs read the FEDERATED fleet histograms (good =
        estimated observations at-or-under the threshold), except
        `e2e`, which reads the tier's own end-to-end histogram (it
        includes retry legs — the user-experienced latency).
        `availability` reads the tier's outcome counters (ok vs all
        settlements)."""
        counts: Dict[str, Tuple[float, float]] = {}
        for spec in self._slo.specs:
            if spec.sli == "availability":
                ok = self._registry.value(
                    "shellac_tier_requests_total", outcome="ok") or 0.0
                total = self._registry.total(
                    "shellac_tier_requests_total") or 0.0
                counts[spec.name] = (float(ok), float(total))
            elif spec.sli == "e2e":
                pairs = self._m.e2e.cumulative_pairs()
                total = pairs[-1][1] if pairs else 0.0
                counts[spec.name] = (
                    cumulative_at(pairs, spec.threshold_s), total
                )
            else:  # ttft / tpot / queue_wait: replica-side, federated
                if self._fleet is None:
                    counts[spec.name] = (0.0, 0.0)
                    continue
                fam = f"shellac_{spec.sli}_seconds"
                buckets, _, count = self._fleet.merged_histogram(fam)
                counts[spec.name] = (
                    cumulative_at(buckets, spec.threshold_s),
                    float(count),
                )
        return counts

    def _slo_exemplar(self, spec: SLOSpec) -> Optional[str]:
        """A violating request's trace id for an alert transition.

        Replica-observed latency SLIs (ttft/tpot/queue_wait) ask the
        replicas themselves: each replica's /debug/requests exposes
        per-bucket trace-id exemplars for exactly these histograms,
        so the id returned names a request whose OWN <sli> landed in
        a bucket above the threshold. Transitions are rare, so the
        few bounded GETs are cheap. Fallbacks, in order: the tier's
        own e2e exemplars (best effort — the slowest recent request
        end-to-end, the most likely violator a tier-side view alone
        can name; e2e > T does NOT prove ttft > T), then the most
        recent badly-settled recorder event (the availability path)."""
        if spec.threshold_s is not None:
            if spec.sli != "e2e":
                tid = self._replica_exemplar(spec.sli, spec.threshold_s)
                if tid is not None:
                    return tid
            best_le, best_tid = -1.0, None
            for le, tid in self._m.e2e.bucket_exemplars().items():
                v = float("inf") if le == "+Inf" else float(le)
                if v > spec.threshold_s and v > best_le:
                    best_le, best_tid = v, tid
            if best_tid is not None:
                return best_tid
        for ev in reversed(self._recorder.tail(256)):
            if ev.get("trace") and ev.get("event") in (
                "tier-exhausted", "stream-severed", "retry"
            ):
                return ev["trace"]
        return None

    def _replica_exemplar(self, sli: str,
                          threshold: float) -> Optional[str]:
        """Highest-bucket exemplar above `threshold` for one replica
        histogram family, scanned across routable replicas' /debug
        exemplar maps. Failures skip the replica — an exemplar lookup
        must never break alerting."""
        best_le, best_tid = -1.0, None
        for rep in self._replicas:
            if not rep.routable:
                continue
            try:
                status, body = self._get(rep.url, "/debug/requests",
                                         self.health_timeout)
                if status != 200:
                    continue
                exemplars = json.loads(body).get("exemplars", {})
            except (OSError, ValueError,
                    http.client.HTTPException):
                continue
            for le, tid in (exemplars.get(sli) or {}).items():
                try:
                    v = float("inf") if le == "+Inf" else float(le)
                except ValueError:
                    continue
                if v > threshold and v > best_le:
                    best_le, best_tid = v, tid
        return best_tid

    # ---- incident black box ------------------------------------------

    @property
    def incidents(self) -> Optional[IncidentManager]:
        return self._incidents

    @property
    def spool(self) -> Optional[EventSpool]:
        return self._spool

    def _incident(self, trigger: str, *,
                  trace_id: Optional[str] = None,
                  detail: Optional[Dict[str, Any]] = None) -> None:
        """Fire one trigger ASYNCHRONOUSLY (no-op without
        --incident-dir). Every automatic tier trigger sits on a
        request-serving or polling thread, and the bundle's federated
        evidence fetch pays up to 2 x health_timeout per replica — a
        client waiting on its 502, or the health sweep, must not wait
        for that. The manager's rate limiter (checked inside
        trigger(), thread-safe) absorbs storms — a severed-stream
        cascade yields a handful of bundles AND a handful of threads,
        not thousands."""
        if self._incidents is None:
            return
        if not self._incidents.would_allow():
            # Storm path: count the drop synchronously (guaranteed
            # cheap — no limiter re-check, no bundle, no thread)
            # instead of spawning a thread per failed request just to
            # have the limiter kill it.
            self._incidents.record_drop(trigger, trace_id=trace_id)
            return
        threading.Thread(
            target=self._incidents.trigger, args=(trigger,),
            kwargs={"trace_id": trace_id, "detail": detail},
            daemon=True, name="shellac-tier-incident",
        ).start()

    def _slo_transitioned(self, spec: SLOSpec, old: str, new: str,
                          transition: Dict[str, Any]) -> None:
        """SLOEngine transition hook: a PAGE landing auto-captures an
        evidence bundle whose manifest carries the violating request's
        trace-id exemplar — the committed counterpart of the pager
        firing. Warnings and recoveries only alert; evidence is for
        pages."""
        if self._autoscaler is not None:
            # Every transition, not just pages: a recovery to ok
            # DISARMS a pending scale-out (see Autoscaler docs).
            self._autoscaler.on_slo_transition(spec.name, old, new)
        if new != "page":
            return
        self._incident(
            "slo-page",
            trace_id=transition.get("exemplar"),
            detail={"slo": spec.name, "from": old, "to": new,
                    "burn": transition.get("burn")},
        )

    def _fleet_evidence(self) -> Dict[str, Any]:
        """Federated evidence fetch: every replica's in-flight table
        and incident list, pulled at trigger time (bounded by the
        health timeout, best-effort per replica — a dead replica is
        part of the story, not a reason to lose the bundle)."""
        out: Dict[str, Any] = {}
        for rep in self._replicas:
            row: Dict[str, Any] = {"state": rep.state,
                                   "role": rep.role}
            for key, path in (("requests", "/debug/requests"),
                              ("incidents", "/debug/incidents")):
                try:
                    status, body = self._get(rep.url, path,
                                             self.health_timeout)
                    row[key] = (json.loads(body) if status == 200
                                else {"status": status})
                except (OSError, ValueError,
                        http.client.HTTPException) as e:
                    row[key] = {"error": f"{type(e).__name__}: {e}"}
            out[rep.url] = row
        return out

    @property
    def slo_enabled(self) -> bool:
        return self._slo is not None

    def slo_status(self) -> Dict[str, Any]:
        """The GET /slo payload."""
        return {
            "slos": self._slo.status() if self._slo is not None else [],
            "page_burn": (self._slo.page_burn
                          if self._slo is not None else None),
            "warn_burn": (self._slo.warn_burn
                          if self._slo is not None else None),
        }

    @property
    def metrics_enabled(self) -> bool:
        return self._registry.enabled

    def metrics_text(self) -> str:
        """The tier's full exposition: its own shellac_tier_* (and
        shellac_slo_*) series, then the federated block — every
        replica series re-labeled `replica="<url>"`, staleness stamps,
        and the shellac_fleet_* aggregates."""
        base = self._registry.render()
        if self._fleet is None:
            return base
        fed = self._fleet.render(
            routable_count=sum(r.routable for r in self._replicas),
            skip_families=frozenset(self._registry.family_names()),
        )
        return base + fed

    @property
    def debug_enabled(self) -> bool:
        return self._debug

    @property
    def recorder(self) -> FlightRecorder:
        return self._recorder

    def debug_requests(self) -> Dict[str, Any]:
        """GET /debug/requests on the tier: recent recorder events
        (attempt log, ejections, severed streams), ring stats, and the
        e2e histogram's exemplars — each exemplar trace id resolves to
        a full timeline here (tier legs) and on the replica that
        served it (engine legs)."""
        out = {
            "recent_events": self._recorder.tail(256),
            "recorder": self._recorder.stats(),
            "exemplars": {"e2e": self._m.e2e.bucket_exemplars()},
            "replicas": [r.snapshot() for r in self._replicas],
        }
        if self._spool is not None:
            out["spool"] = self._spool.stats()
        if self._incidents is not None:
            out["last_incident"] = self._incidents.last
        return out

    def debug_request(self, trace_id: str) -> Optional[Dict[str, Any]]:
        events = self._recorder.events_for(trace_id)
        source = "ring"
        if not events and self._spool is not None:
            events = self._spool.events_for(trace_id)
            source = "spool"
        if not events:
            return None
        return {"trace_id": trace_id, "events": events,
                "source": source}

    def close(self) -> None:
        self._closed.set()
        self._poller.join(timeout=5)
        self._poll_pool.shutdown(wait=False)
        if self._fabric_pool is not None:
            self._fabric_pool.shutdown(wait=False)
        if self._spool is not None:
            self._spool.close()


def make_tier_http_server(router: TierRouter, host: str = "127.0.0.1",
                          port: int = 0) -> ThreadingHTTPServer:
    route_paths = ("/generate", "/v1/completions", "/v1/chat/completions")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _send(self, code: int, obj,
                  trace_id: Optional[str] = None,
                  retry_after_s: Optional[float] = None) -> None:
            if isinstance(obj, tuple):  # (status, body, content_type)
                code, body, ct = obj
            else:
                body, ct = json.dumps(obj).encode(), "application/json"
            self.send_response(code)
            self.send_header("Content-Type", ct)
            self.send_header("Content-Length", str(len(body)))
            if trace_id is not None:
                self.send_header(REQUEST_ID_HEADER, trace_id)
            if retry_after_s is not None:
                # Informed hint (a tenant throttle knows its bucket's
                # refill horizon) — still jitter-widened by the caller
                # so one tenant's clients don't re-arrive in a spike.
                self.send_header("Retry-After",
                                 str(max(1, int(round(retry_after_s)))))
            elif code in (429, 502, 503, 504):
                from shellac_tpu.inference.server import retry_after

                self.send_header(
                    "Retry-After",
                    str(max(1, int(round(retry_after(1.0, 4.0))))),
                )
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            # Errors carry the trace id too (adopted or minted): a
            # rejected request is exactly the one its sender wants to
            # look up in the recorder.
            tid, _ = adopt_trace(self.headers.get(TRACE_HEADER))
            if self.path == "/health":
                h = router.health()
                self._send(200 if h["ok"] else 503, h, trace_id=tid)
            elif self.path == "/stats":
                self._send(200, router.stats())
            elif self.path == "/slo":
                if not router.slo_enabled:
                    self._send(404, {
                        "error": "no SLOs configured "
                                 "(serve-tier --slo/--slo-file)",
                    }, trace_id=tid)
                else:
                    self._send(200, router.slo_status())
            elif self.path == "/metrics":
                if not router.metrics_enabled:
                    self._send(404, {"error": "metrics disabled"},
                               trace_id=tid)
                    return
                body = router.metrics_text().encode()
                self._send(200, (
                    200, body, "text/plain; version=0.0.4; charset=utf-8",
                ))
            elif self.path == "/v1/models":
                # Forward from any routable replica (the tier serves
                # whatever its replicas serve).
                for rep in router.replicas:
                    if not rep.routable:
                        continue
                    try:
                        status, body = router._get(
                            rep.url, "/v1/models", router.health_timeout
                        )
                        if status == 200:
                            self._send(200, (
                                200, body, "application/json"))
                            return
                    except (OSError, http.client.HTTPException):
                        continue
                self._send(503, {"error": "no routable replica"},
                           trace_id=tid)
            elif self.path.startswith("/debug/"):
                if not router.debug_enabled:
                    self._send(404, {"error": "debug endpoints disabled "
                                              "(serve-tier --no-debug)"},
                               trace_id=tid)
                elif self.path == "/debug/requests":
                    self._send(200, router.debug_requests())
                elif self.path == "/debug/incidents":
                    if router.incidents is None:
                        self._send(400, {
                            "error": "incident bundles need "
                                     "serve-tier --incident-dir",
                        }, trace_id=tid)
                    else:
                        self._send(200, {
                            "incidents": router.incidents.list(),
                            "dir": router.incidents.incident_dir,
                            "last": router.incidents.last,
                        })
                elif self.path.startswith("/debug/incident/"):
                    bid = self.path[len("/debug/incident/"):]
                    out = (router.incidents.load(bid)
                           if router.incidents is not None else None)
                    if out is None:
                        self._send(404, {
                            "error": f"no incident bundle {bid!r}",
                        }, trace_id=tid)
                    else:
                        self._send(200, out)
                elif self.path.startswith("/debug/request/"):
                    qid = self.path[len("/debug/request/"):]
                    out = router.debug_request(qid)
                    if out is None:
                        self._send(404, {
                            "error": f"no recorded events for trace "
                                     f"id {qid!r}",
                        }, trace_id=tid)
                    else:
                        self._send(200, out)
                else:
                    self._send(404, {"error": "not found"},
                               trace_id=tid)
            else:
                self._send(404, {"error": "not found"}, trace_id=tid)

        @staticmethod
        def _stream_terminated(tail: bytes, sse: bool) -> bool:
            """Did the stream END, or merely stop? A replica exiting
            cleanly mid-stream delivers a polite FIN the byte pump
            cannot tell from completion — so completion is checked
            against the protocol's terminator: the `[DONE]` sentinel /
            an error event (SSE), or a final record carrying `done` or
            `error` (ndjson). Anything else is truncation and must be
            reported loudly, never relayed as success."""
            lines = [ln for ln in tail.strip().splitlines() if ln.strip()]
            if not lines:
                return False
            last = lines[-1].strip()
            if sse:
                if not last.startswith(b"data: "):
                    return False
                last = last[len(b"data: "):]
                if last == b"[DONE]":
                    return True
            try:
                obj = json.loads(last)
            except ValueError:
                return False
            return isinstance(obj, dict) and (
                bool(obj.get("done")) or "error" in obj
            )

        def _relay_stream(self, path: str, payload: dict,
                          trace_id: str) -> None:
            opened, err = router.open_stream(path, payload,
                                             trace_id=trace_id)
            if opened is None:
                self._send(err[0], err, trace_id=trace_id)
                return
            resp, first, ct, rep_url, t0 = opened
            self.send_response(200)
            self.send_header("Content-Type", ct)
            self.send_header(REQUEST_ID_HEADER, trace_id)
            if ct.startswith("text/event-stream"):
                self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            sse = ct.startswith("text/event-stream")
            upstream_lost = False
            tail = first[-2048:]
            try:
                self.wfile.write(first)
                self.wfile.flush()
                while True:
                    try:
                        chunk = resp.read(4096)
                    except (OSError, http.client.HTTPException):
                        # The REPLICA died mid-stream (RST), after
                        # bytes already reached the client: non-
                        # retryable by contract (a retry would
                        # silently duplicate the partial completion) —
                        # fail LOUDLY with an in-band record instead.
                        upstream_lost = True
                        break
                    if not chunk:
                        # Clean EOF — which is only success if the
                        # protocol terminator actually arrived.
                        upstream_lost = not self._stream_terminated(
                            tail, sse)
                        break
                    tail = (tail + chunk)[-2048:]
                    self.wfile.write(chunk)
                    self.wfile.flush()
                if upstream_lost:
                    router._m.stream_severed.labels(
                        replica=rep_url).inc()
                    router._recorder.record(
                        trace_id, "stream-severed", src="tier",
                        replica=rep_url,
                    )
                    # A severed stream is a client-visible data loss:
                    # capture the evidence while the dying replica's
                    # last federated numbers are still fresh.
                    router._incident(
                        "stream-severed", trace_id=trace_id,
                        detail={"replica": rep_url},
                    )
                    # The loud in-band record carries the trace id, so
                    # the client's capture alone identifies the severed
                    # request in the tier's attempt log and the
                    # replica's flight recorder.
                    msg = {"error": {
                        "message": "upstream replica lost mid-stream",
                        "type": "server_error", "retryable": False,
                        "trace_id": trace_id,
                    }}
                    data = json.dumps(msg)
                    self.wfile.write(
                        (f"data: {data}\n\n" if sse
                         else data + "\n").encode()
                    )
            except OSError:
                # OUR client hung up (the normal cancel path): closing
                # the upstream response propagates the disconnect to
                # the replica, whose engine-side cancel frees the slot.
                pass
            finally:
                resp.close()
                # The e2e histogram covers the WHOLE stream (its help
                # text says admission to final byte), so it settles
                # here, not at the first event — exemplar included,
                # like every non-streamed settlement.
                router._m.e2e.observe(time.monotonic() - t0,
                                      exemplar=trace_id)

        def do_POST(self):
            # Adopt the client's trace id (a W3C-shaped x-shellac-trace
            # from an upstream proxy) or mint one BEFORE parsing the
            # payload: this id rides every replica attempt, comes back
            # as x-request-id — and a 400 for a malformed body is
            # exactly the response its sender wants an id on.
            tid, _ = adopt_trace(self.headers.get(TRACE_HEADER))
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
            except ValueError:
                self._send(400, {"error": "bad JSON payload"},
                           trace_id=tid)
                return
            if not isinstance(payload, dict):
                # Valid JSON that isn't an object ('[1]', '5') must
                # 400, not AttributeError the handler thread.
                self._send(400, {"error": "payload must be a JSON "
                                          "object"}, trace_id=tid)
                return
            if self.path == "/debug/incident":
                # Manual tier-side evidence bundle.
                if not router.debug_enabled:
                    self._send(404, {"error": "debug endpoints "
                                              "disabled"},
                               trace_id=tid)
                    return
                if router.incidents is None:
                    self._send(400, {"error": "incident bundles need "
                                              "serve-tier "
                                              "--incident-dir"},
                               trace_id=tid)
                    return
                detail = {"via": "POST /debug/incident"}
                if payload.get("note") is not None:
                    detail["note"] = str(payload["note"])[:1024]
                errors_before = router.incidents.write_errors
                bid = router.incidents.trigger("manual", trace_id=tid,
                                               detail=detail)
                if bid is None:
                    if router.incidents.write_errors > errors_before:
                        self._send(500, {"error": "incident bundle "
                                                  "write failed"},
                                   trace_id=tid)
                        return
                    self._send(429, {"error": "incident trigger "
                                              "rate-limited"},
                               trace_id=tid)
                    return
                self._send(200, {"incident": bid}, trace_id=tid)
                return
            if self.path == "/admin/drain":
                if "replica" not in payload:
                    # No default: a typoed request must not silently
                    # drain whichever replica happens to be first.
                    self._send(400, {"error": 'need "replica": '
                                              "url or index"},
                               trace_id=tid)
                    return
                try:
                    out = router.drain_replica(
                        payload["replica"],
                        resume=bool(payload.get("resume")),
                    )
                except (ValueError, IndexError) as e:
                    self._send(400, {"error": str(e)}, trace_id=tid)
                    return
                except OSError as e:
                    self._send(502, {"error": f"drain forward failed: {e}"},
                               trace_id=tid)
                    return
                self._send(200, out)
                return
            if self.path not in route_paths:
                self._send(404, {"error": "not found"}, trace_id=tid)
                return
            # Tenant identity: the explicit header wins; the OpenAI
            # `user` field is adopted on the OpenAI surfaces (the same
            # precedence the replicas apply); otherwise anonymous.
            tenant = (self.headers.get(TENANT_HEADER) or "").strip() \
                or None
            if (tenant is None and self.path != "/generate"
                    and isinstance(payload.get("user"), str)
                    and payload["user"]):
                tenant = payload["user"]
            release = None
            if router._admission is not None:
                name = tenant or ANONYMOUS
                ok, why, wait = router._admission.admit(
                    name, TierRouter._admission_cost(payload)
                )
                if not ok:
                    router._m.tenant_throttles.labels(
                        tenant=name, reason=why).inc()
                    router._recorder.record(
                        tid, "tenant-throttle", src="tier",
                        tenant=name, reason=why,
                    )
                    from shellac_tpu.inference.server import \
                        retry_after

                    lo = max(wait, 0.5)
                    self._send(
                        429,
                        {"error": "tenant over quota",
                         "reason": why, "tenant": name,
                         "retry_after_s": round(lo, 3)},
                        trace_id=tid,
                        retry_after_s=retry_after(lo, lo + 2.0),
                    )
                    return
                release = name
            if tenant:
                # Rides to the replica as x-shellac-tenant on every
                # attempt (popped back out of the payload in _post).
                payload["_tenant"] = tenant
            try:
                if payload.get("stream"):
                    self._relay_stream(self.path, payload, tid)
                else:
                    self._send(0, router.forward_json(self.path,
                                                      payload,
                                                      trace_id=tid),
                               trace_id=tid)
            finally:
                if release is not None:
                    # The tier's concurrency lease spans the WHOLE
                    # relay (streams included): settled exactly once,
                    # whatever the forward did.
                    router._admission.release(release)

    return ThreadingHTTPServer((host, port), Handler)


def serve_tier(router: TierRouter, host: str = "127.0.0.1",
               port: int = 8100) -> None:
    """Blocking entry point used by `python -m shellac_tpu serve-tier`."""
    httpd = make_tier_http_server(router, host, port)
    print(json.dumps(
        {"serving_tier": f"http://{host}:{httpd.server_address[1]}",
         "replicas": [r.url for r in router.replicas]}
    ), flush=True)
    try:
        httpd.serve_forever()
    finally:
        router.close()
