"""Multi-host (multi-process) serving: one engine, SPMD across hosts.

On a TPU pod each host owns a slice of the devices; a program that
touches a globally-sharded array must run the SAME jitted computations
in the SAME order on every host, or the runtime deadlocks. A serving
engine is host-driven — admissions, slot scheduling, stop checks — so
the host decisions themselves must be replicated, not just the math.

This wrapper makes the engine's host side deterministic-by-broadcast:

  - every process builds the same engine over the same global mesh
    (same config, same sharded params, same seed);
  - process 0 is the PRIMARY: it owns the public submit/cancel surface
    and buffers them as commands;
  - each step() first broadcasts the buffered command list (device
    collective via multihost_utils — it rides the same interconnect as
    the model, no side channel to configure), then every process
    applies the commands to its local engine replica and runs
    engine.step() in lockstep.

Everything downstream is already deterministic given the command
stream: prompt hashes, the paged free list, jax PRNG keys from the
shared seed, and the decoded tokens (each process device_gets the same
replicated values). So the engines stay bit-identical without any
further synchronization — proven by the two-process test, which runs
real cross-process collectives on the CPU backend
(tests/test_multihost_serving.py).

Follower processes never see requests; they sit in serve_forever(),
which steps until the primary broadcasts shutdown. The primary's
typical loop is the HTTP server's scheduler thread, with submissions
flowing through this wrapper instead of straight into the engine.

The reference repo for this project is empty (SURVEY.md §0); there is
no upstream multi-host serving stack to cite.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

_STOP = "stop"


class MultihostEngine:
    """Lockstep driver for a BatchingEngine replicated across processes.

    Single-process jobs degenerate cleanly: broadcasts are identity and
    the wrapper is a thin pass-through, so the same serving code runs
    on one host or many.
    """

    def __init__(self, engine):
        self.engine = engine
        self.process_index = jax.process_index()
        self.is_primary = self.process_index == 0
        # Tells the HTTP server's scheduler to step every loop even
        # when idle, so followers are never parked in a broadcast
        # longer than the transport tolerates.
        self.needs_heartbeat = jax.process_count() > 1
        self._pending: List[Tuple[str, tuple, dict]] = []
        self._stopped = False

    # ---- primary-side surface (mirrors BatchingEngine) ---------------

    def submit(self, rid, tokens, max_new: int, **kw) -> None:
        """Queue a request (primary only; followers get it by broadcast).

        Arguments are validated HERE, on the primary, by a dry
        validation pass against the local engine, so a bad request
        raises at submit time instead of poisoning every process's
        command stream mid-step.
        """
        self._require_primary("submit")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        self.engine.submit(rid, tokens, max_new, **kw)
        # The local submit doubles as validation AND the primary's own
        # application of the command; followers replay it at step().
        self._pending.append(("submit", (rid, tokens.tolist(), max_new), kw))

    def cancel(self, rid) -> bool:
        self._require_primary("cancel")
        hit = self.engine.cancel(rid)
        if hit:
            self._pending.append(("cancel", (rid,), {}))
        return hit

    def shutdown(self) -> None:
        """Release the followers (their serve_forever returns); the
        primary's own engine is left as-is. Idempotent: a second call
        must not broadcast at followers that already exited."""
        self._require_primary("shutdown")
        if self._stopped:
            return
        self._pending.append((_STOP, (), {}))
        self._exchange()
        self._stopped = True

    @property
    def pending(self) -> int:
        return self.engine.pending

    @property
    def stats(self):
        return self.engine.stats

    def __getattr__(self, name):
        # Read-only pass-through for the surfaces the HTTP server
        # inspects on a bare engine (n_slots, logprobs,
        # finished_logprobs, _slots, _defaults, ...). Only fires for
        # names not defined on the wrapper itself.
        return getattr(self.engine, name)

    def _require_primary(self, what: str) -> None:
        if not self.is_primary:
            raise RuntimeError(
                f"{what}() is primary-only (process 0); this is process "
                f"{self.process_index} — followers call serve_forever()"
            )

    # ---- lockstep step ----------------------------------------------

    def step(self) -> Optional[List[Tuple[Any, List[int]]]]:
        """Broadcast buffered commands, apply, advance every engine one
        step. Returns finished requests, or None once shut down."""
        if self._stopped:
            return None
        for op, args, kw in self._exchange():
            if op == _STOP:
                self._stopped = True
                return None
            if self.is_primary:
                continue  # already applied at submit/cancel time
            if op == "submit":
                rid, tokens, max_new = args
                self.engine.submit(rid, tokens, max_new, **kw)
            elif op == "cancel":
                self.engine.cancel(*args)
        return self.engine.step()

    def serve_forever(self) -> None:
        """Follower loop: step in lockstep until the primary shuts down."""
        while self.step() is not None:
            pass

    def run(self, requests=None):
        """Drain helper, same contract as BatchingEngine.run. On the
        primary, submits and steps to empty then shuts the job down;
        followers must be in serve_forever()."""
        self._require_primary("run")
        for r in requests or ():
            self.submit(*r)
        results = {}
        while self.pending:
            for rid, out in self.step():
                results[rid] = out
        self.shutdown()
        return results

    # ---- transport ---------------------------------------------------

    def _exchange(self) -> List[Tuple[str, tuple, dict]]:
        """Ship the primary's command buffer to every process.

        Two broadcasts: a fixed-shape length, then the pickled payload
        (skipped when empty — the overwhelmingly common decode tick).
        multihost_utils routes these through a jitted device collective,
        so no extra transport needs to exist or be configured.
        """
        from jax.experimental import multihost_utils as mhu

        if jax.process_count() == 1:
            cmds, self._pending = self._pending, []
            return cmds
        payload = (pickle.dumps(self._pending)
                   if self.is_primary and self._pending else b"")
        self._pending = []
        size = int(mhu.broadcast_one_to_all(
            np.asarray([len(payload)], np.int32)
        )[0])
        if size == 0:
            return []
        buf = np.zeros((size,), np.uint8)
        if self.is_primary:
            buf[:] = np.frombuffer(payload, np.uint8)
        buf = np.asarray(mhu.broadcast_one_to_all(buf))
        return pickle.loads(buf.tobytes())
