"""Multi-host (multi-process) serving: one engine, SPMD across hosts.

On a TPU pod each host owns a slice of the devices; a program that
touches a globally-sharded array must run the SAME jitted computations
in the SAME order on every host, or the runtime deadlocks. A serving
engine is host-driven — admissions, slot scheduling, stop checks — so
the host decisions themselves must be replicated, not just the math.

This wrapper makes the engine's host side deterministic-by-broadcast:

  - every process builds the same engine over the same global mesh
    (same config, same sharded params, same seed);
  - process 0 is the PRIMARY: it owns the public submit/cancel surface
    and buffers them as commands;
  - each step() first broadcasts the buffered command list (device
    collective via multihost_utils — it rides the same interconnect as
    the model, no side channel to configure), then every process
    applies the commands to its local engine replica and runs
    engine.step() in lockstep.

Everything downstream is already deterministic given the command
stream: prompt hashes, the paged free list, jax PRNG keys from the
shared seed, and the decoded tokens (each process device_gets the same
replicated values). So the engines stay bit-identical without any
further synchronization — proven by the two-process test, which runs
real cross-process collectives on the CPU backend
(tests/test_multihost_serving.py).

Follower processes never see requests; they sit in serve_forever(),
which steps until the primary broadcasts shutdown. The primary's
typical loop is the HTTP server's scheduler thread, with submissions
flowing through this wrapper instead of straight into the engine.

The reference repo for this project is empty (SURVEY.md §0); there is
no upstream multi-host serving stack to cite.
"""

from __future__ import annotations

import pickle
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

_STOP = "stop"


class MultihostEngine:
    """Lockstep driver for a BatchingEngine replicated across processes.

    Single-process jobs degenerate cleanly: broadcasts are identity and
    the wrapper is a thin pass-through, so the same serving code runs
    on one host or many.
    """

    def __init__(self, engine):
        self.engine = engine
        self.process_index = jax.process_index()
        self.is_primary = self.process_index == 0
        # Tells the HTTP server's scheduler to step every loop even
        # when idle, so followers are never parked in a broadcast
        # longer than the transport tolerates.
        self.needs_heartbeat = jax.process_count() > 1
        self._pending: List[Tuple[str, tuple, dict]] = []
        self._stopped = False
        # Serving epoch: bumped by the primary's supervisor on recovery
        # (resync()); the bump rides the command broadcast so followers
        # drop the same in-flight work the primary just dropped.
        self.epoch = 0

    # ---- primary-side surface (mirrors BatchingEngine) ---------------

    def submit(self, rid, tokens, max_new: int, **kw) -> None:
        """Queue a request (primary only; followers get it by broadcast).

        Arguments are validated HERE, on the primary, by a dry
        validation pass against the local engine, so a bad request
        raises at submit time instead of poisoning every process's
        command stream mid-step.
        """
        self._require_primary("submit")
        tokens = np.asarray(tokens, np.int32).reshape(-1)
        # The trace span is host-local observability: apply it on the
        # primary's engine but strip it from the broadcast — a
        # RequestTrace neither pickles nor means anything on a
        # follower, and followers' spans would double-count.
        trace = kw.pop("trace", None)
        self.engine.submit(rid, tokens, max_new, trace=trace, **kw)
        # The local submit doubles as validation AND the primary's own
        # application of the command; followers replay it at step().
        self._pending.append(("submit", (rid, tokens.tolist(), max_new), kw))

    def cancel(self, rid) -> bool:
        self._require_primary("cancel")
        hit = self.engine.cancel(rid)
        if hit:
            self._pending.append(("cancel", (rid,), {}))
        return hit

    def shutdown(self) -> None:
        """Release the followers (their serve_forever returns); the
        primary's own engine is left as-is. Idempotent: a second call
        must not broadcast at followers that already exited."""
        self._require_primary("shutdown")
        if self._stopped:
            return
        self._pending.append((_STOP, (), {}))
        self._exchange()
        self._stopped = True

    def resync(self) -> "MultihostEngine":
        """Supervisor recovery hook (primary only): bump the serving
        epoch, drop every local queued/in-flight request, and buffer the
        epoch command so followers drop the same work at the next
        step's broadcast instead of wedging on a collective for a
        request the primary no longer tracks. Returns self, so it slots
        in as the server's engine_factory.

        Scope: this recovers the SCHEDULER-DEATH class of faults — the
        step raised (a follower was preempted and replaced, a transient
        transport error) but the process group is still alive, so the
        next broadcast goes through. A step wedged in native code (dead
        follower mid-collective on a real pod) cannot be resynced
        in-process: the old scheduler thread never returns and still
        owns this engine, so the supervisor refuses the in-place
        factory and goes fatal IMMEDIATELY on a wedge — no restart
        budget is consumed ("restart the pod")."""
        self._require_primary("resync")
        if self._stopped:
            raise RuntimeError("resync() after shutdown: followers are "
                               "released and cannot rejoin this job")
        self.epoch += 1
        self._apply_epoch(self.epoch)
        self._pending.append(("epoch", (self.epoch,), {}))
        return self

    def _apply_epoch(self, epoch: int) -> None:
        """Reset the local replica to the epoch's canonical state:
        no in-flight work, and the sampling PRNG re-keyed from
        (construction seed, epoch). The re-key is what restores
        bit-identity after a follower is REPLACED (its fresh engine
        starts at the seed while survivors' keys were split once per
        served decode step — without this, the first sampled request
        after recovery would diverge across hosts and wedge the pod
        all over again); folding the retained seed keeps post-recovery
        sampling seed-dependent and reproducible."""
        self.epoch = epoch
        self.engine.abort_all()
        self.engine._key = jax.random.fold_in(
            jax.random.PRNGKey(getattr(self.engine, "seed", 0)), epoch
        )

    @property
    def pending(self) -> int:
        return self.engine.pending

    @property
    def stats(self):
        return self.engine.stats

    def __getattr__(self, name):
        # Read-only pass-through for the surfaces the HTTP server
        # inspects on a bare engine (n_slots, logprobs,
        # finished_logprobs, _slots, _defaults, ...). Only fires for
        # names not defined on the wrapper itself.
        return getattr(self.engine, name)

    def _require_primary(self, what: str) -> None:
        if not self.is_primary:
            raise RuntimeError(
                f"{what}() is primary-only (process 0); this is process "
                f"{self.process_index} — followers call serve_forever()"
            )

    # ---- lockstep step ----------------------------------------------

    def step(self) -> Optional[List[Tuple[Any, List[int]]]]:
        """Broadcast buffered commands, apply, advance every engine one
        step. Returns finished requests, or None once shut down."""
        if self._stopped:
            return None
        for op, args, kw in self._exchange():
            if op == _STOP:
                self._stopped = True
                return None
            if op == "epoch":
                # Epoch bump: the primary's supervisor recovered and
                # reset its replica; mirror that here (drop in-flight
                # work, re-key the PRNG from the epoch) so the replicas
                # re-enter lockstep on identical state. The primary
                # already applied its side in resync().
                if not self.is_primary:
                    self._apply_epoch(args[0])
                continue
            if self.is_primary:
                continue  # already applied at submit/cancel time
            if op == "submit":
                rid, tokens, max_new = args
                self.engine.submit(rid, tokens, max_new, **kw)
            elif op == "cancel":
                self.engine.cancel(*args)
        return self.engine.step()

    def serve_forever(self, *, fault_budget: int = 0,
                      fault_window: float = 300.0) -> None:
        """Follower loop: step in lockstep until the primary shuts
        down.

        fault_budget (default 0 = any exception re-raises, the loud
        legacy contract) opts into the supervisor's recovery story —
        wire it to the SAME value as the primary's restart budget. A
        replicated engine-step exception (the deterministic
        scheduler-death class — it raises on EVERY host, not just the
        primary) is then survivable: the follower drops its local work
        and keeps participating in the command stream, so the
        primary's epoch bump can resynchronize it instead of finding
        no peers left for the next broadcast. A fault local to THIS
        follower cannot be absorbed that way — the other replicas kept
        their state, the next collective wedges, and the primary's
        step watchdog turns the pod fatal (which is why the docs
        require --step-timeout alongside a multi-host restart budget);
        a dead transport raising on every exchange exhausts the budget
        in seconds and re-raises, keeping total-loss failures loud."""
        from shellac_tpu.utils.failure import RestartBudget

        budget = RestartBudget(fault_budget, fault_window)
        while True:
            try:
                if self.step() is None:
                    return
            except Exception:
                if not budget.allow():
                    raise
                self.engine.abort_all()

    def run(self, requests=None):
        """Drain helper, same contract as BatchingEngine.run. On the
        primary, submits and steps to empty then shuts the job down;
        followers must be in serve_forever()."""
        self._require_primary("run")
        for r in requests or ():
            self.submit(*r)
        results = {}
        while self.pending:
            for rid, out in self.step():
                results[rid] = out
        self.shutdown()
        return results

    # ---- transport ---------------------------------------------------

    def _exchange(self) -> List[Tuple[str, tuple, dict]]:
        """Ship the primary's command buffer to every process.

        Two broadcasts: a fixed-shape length, then the pickled payload
        (skipped when empty — the overwhelmingly common decode tick).
        multihost_utils routes these through a jitted device collective,
        so no extra transport needs to exist or be configured.
        """
        from jax.experimental import multihost_utils as mhu

        if jax.process_count() == 1:
            cmds, self._pending = self._pending, []
            return cmds
        payload = (pickle.dumps(self._pending)
                   if self.is_primary and self._pending else b"")
        self._pending = []
        size = int(mhu.broadcast_one_to_all(
            np.asarray([len(payload)], np.int32)
        )[0])
        if size == 0:
            return []
        buf = np.zeros((size,), np.uint8)
        if self.is_primary:
            buf[:] = np.frombuffer(payload, np.uint8)
        buf = np.asarray(mhu.broadcast_one_to_all(buf))
        return pickle.loads(buf.tobytes())
