"""Multi-tenant QoS primitives: tenant identity, admission quotas, and
weighted-fair queueing.

Three layers, one module, zero jax:

- **Tenant identity.** A request's tenant id arrives as the
  ``x-shellac-tenant`` header (or the OpenAI ``user`` field) and
  defaults to ``anonymous``. `TenantPolicy` maps tenant ids to
  `TenantSpec`s (rate, burst, max_concurrency, priority class, weight)
  parsed from ``--tenant-config`` JSON — unknown tenants fall to the
  ``default`` spec, so one flooding client can never consume another
  tenant's admission budget.

- **Admission.** `AdmissionController` enforces each tenant's token
  bucket (rate/burst over estimated tokens = prompt + max_new) and
  concurrency quota. Over-quota answers are (reason, retry_after)
  pairs the server turns into 429 + jittered Retry-After; admitted
  requests hold a concurrency lease the caller releases at settle.

- **Scheduling.** `WeightedFairQueue` is a drop-in replacement for the
  engine's FIFO pending deque: deficit-round-robin over priority-class
  lanes, cost measured in tokens, each lane's quantum scaled by the
  waiting request's weight. With a single class in play it degenerates
  to FIFO exactly — the pre-QoS engine order, bit for bit.

The cost model follows the characterize-don't-guess discipline: the
bucket charges measured token counts and the preemption victim rule
(server-side) ranks by `bytes_per_token()`-measured resident bytes,
never by guessed request "sizes".
"""

from __future__ import annotations

import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

#: HTTP header carrying the tenant id (the `x-shellac-trace` twin —
#: forwarded by the tier on every retry attempt).
TENANT_HEADER = "x-shellac-tenant"

#: The tenant id of requests that declare none.
ANONYMOUS = "anonymous"

#: Priority classes, best-first. Lower value = scheduled sooner and
#: never preempted by a lower class.
PRIORITY_CLASSES = {"interactive": 0, "standard": 1, "batch": 2}
CLASS_NAMES = {v: k for k, v in PRIORITY_CLASSES.items()}

#: Default DRR weight per class (token-share ratio 8:4:1).
DEFAULT_WEIGHTS = {"interactive": 8.0, "standard": 4.0, "batch": 1.0}

DEFAULT_CLASS = "standard"


@dataclass(frozen=True)
class TenantSpec:
    """One tenant's quota + scheduling contract. `None` rate or
    max_concurrency means unlimited (the seed behavior)."""

    name: str
    rate: Optional[float] = None  # tokens/second refill
    burst: Optional[float] = None  # bucket depth, tokens
    max_concurrency: Optional[int] = None
    priority: str = DEFAULT_CLASS
    weight: Optional[float] = None  # DRR weight; None = class default

    @property
    def qos_class(self) -> int:
        return PRIORITY_CLASSES[self.priority]

    @property
    def qos_weight(self) -> float:
        if self.weight is not None:
            return float(self.weight)
        return DEFAULT_WEIGHTS[self.priority]


def _parse_spec(name: str, raw: Any) -> TenantSpec:
    if not isinstance(raw, dict):
        raise ValueError(
            f"tenant-config[{name!r}]: expected an object, got "
            f"{type(raw).__name__}"
        )
    unknown = set(raw) - {"rate", "burst", "max_concurrency",
                          "priority", "weight"}
    if unknown:
        raise ValueError(
            f"tenant-config[{name!r}]: unknown keys {sorted(unknown)} "
            "(allowed: rate, burst, max_concurrency, priority, weight)"
        )
    rate = raw.get("rate")
    burst = raw.get("burst")
    maxc = raw.get("max_concurrency")
    prio = raw.get("priority", DEFAULT_CLASS)
    weight = raw.get("weight")
    if rate is not None:
        rate = float(rate)
        if rate <= 0:
            raise ValueError(
                f"tenant-config[{name!r}]: rate must be > 0 tokens/s "
                "(omit it for unlimited)"
            )
    if burst is not None:
        burst = float(burst)
        if burst <= 0:
            raise ValueError(
                f"tenant-config[{name!r}]: burst must be > 0 tokens"
            )
    if rate is not None and burst is None:
        # A rate with no declared depth gets one second of headroom —
        # enough to admit a request at the steady rate.
        burst = rate
    if burst is not None and rate is None:
        raise ValueError(
            f"tenant-config[{name!r}]: burst without rate is "
            "meaningless (the bucket would never refill)"
        )
    if maxc is not None:
        maxc = int(maxc)
        if maxc < 1:
            raise ValueError(
                f"tenant-config[{name!r}]: max_concurrency must be "
                ">= 1 (omit it for unlimited)"
            )
    if prio not in PRIORITY_CLASSES:
        raise ValueError(
            f"tenant-config[{name!r}]: unknown priority {prio!r} "
            f"(one of {sorted(PRIORITY_CLASSES)})"
        )
    if weight is not None:
        weight = float(weight)
        if weight <= 0:
            raise ValueError(
                f"tenant-config[{name!r}]: weight must be > 0"
            )
    return TenantSpec(name, rate=rate, burst=burst,
                      max_concurrency=maxc, priority=prio,
                      weight=weight)


class TenantPolicy:
    """The parsed ``--tenant-config``: named tenant specs plus the
    ``default`` spec unknown tenants inherit (quota-free standard
    class when the config names none)."""

    def __init__(self, specs: Dict[str, TenantSpec],
                 default: Optional[TenantSpec] = None):
        self.specs = dict(specs)
        self.default = default or TenantSpec("default")

    @classmethod
    def parse(cls, raw: Any) -> "TenantPolicy":
        """Build from the ``--tenant-config`` JSON: an object mapping
        tenant id -> spec object. The id ``default`` configures the
        fallback for unnamed tenants. Raises ValueError on any
        malformed entry — admission never guesses at a quota."""
        if isinstance(raw, (str, bytes)):
            try:
                raw = json.loads(raw)
            except ValueError as e:
                raise ValueError(f"tenant-config: bad JSON: {e}")
        if not isinstance(raw, dict):
            raise ValueError(
                "tenant-config: expected a JSON object mapping tenant "
                "id -> {rate, burst, max_concurrency, priority, weight}"
            )
        if "tenants" in raw and isinstance(raw["tenants"], dict):
            raw = raw["tenants"]
        specs: Dict[str, TenantSpec] = {}
        default = None
        for name, entry in raw.items():
            if not isinstance(name, str) or not name:
                raise ValueError("tenant-config: tenant ids must be "
                                 "non-empty strings")
            spec = _parse_spec(name, entry)
            if name == "default":
                default = spec
            else:
                specs[name] = spec
        return cls(specs, default)

    def spec(self, tenant: Optional[str]) -> TenantSpec:
        t = tenant or ANONYMOUS
        got = self.specs.get(t)
        if got is not None:
            return got
        d = self.default
        # The fallback keeps each unknown tenant's OWN bucket (keyed
        # by its id) but the default's limits.
        return TenantSpec(t, rate=d.rate, burst=d.burst,
                          max_concurrency=d.max_concurrency,
                          priority=d.priority, weight=d.weight)


class TokenBucket:
    """A monotonic-clock token bucket. Not thread-safe on its own —
    the AdmissionController serializes access."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float, burst: float,
                 now: Optional[float] = None):
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)
        self._t = time.monotonic() if now is None else now

    def _refill(self, now: float) -> None:
        if now > self._t:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, cost: float,
                 now: Optional[float] = None) -> Tuple[bool, float]:
        """(admitted?, seconds until `cost` tokens WILL be available).
        The retry hint is exact for this bucket alone; callers jitter
        it before putting it on the wire."""
        now = time.monotonic() if now is None else now
        self._refill(now)
        if self.tokens >= cost:
            self.tokens -= cost
            return True, 0.0
        need = min(cost, self.burst) - self.tokens
        return False, need / self.rate


class AdmissionController:
    """Per-tenant token buckets + concurrency leases, shared by the
    server's admission path and the tier's edge check. Thread-safe."""

    def __init__(self, policy: TenantPolicy):
        self.policy = policy
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        self._inflight: Dict[str, int] = {}
        # Rolling per-tenant counters for /stats and `top` (admission
        # totals live in the metrics registry; these are the cheap
        # always-on snapshot).
        self.admitted: Dict[str, int] = {}
        self.throttled: Dict[str, int] = {}

    def admit(self, tenant: Optional[str], cost: float,
              now: Optional[float] = None
              ) -> Tuple[bool, Optional[str], float]:
        """(admitted?, throttle reason, retry_after seconds). On
        admission the tenant holds one concurrency lease — release()
        it at settle, NOT at response write (streamed bodies outlive
        the handler)."""
        spec = self.policy.spec(tenant)
        t = spec.name
        with self._lock:
            inflight = self._inflight.get(t, 0)
            if (spec.max_concurrency is not None
                    and inflight >= spec.max_concurrency):
                self.throttled[t] = self.throttled.get(t, 0) + 1
                return False, "concurrency", 1.0
            if spec.rate is not None:
                bucket = self._buckets.get(t)
                if bucket is None or bucket.rate != spec.rate \
                        or bucket.burst != spec.burst:
                    bucket = TokenBucket(spec.rate, spec.burst, now=now)
                    self._buckets[t] = bucket
                ok, wait = bucket.try_take(cost, now=now)
                if not ok:
                    self.throttled[t] = self.throttled.get(t, 0) + 1
                    return False, "rate", wait
            self._inflight[t] = inflight + 1
            self.admitted[t] = self.admitted.get(t, 0) + 1
            return True, None, 0.0

    def release(self, tenant: Optional[str]) -> None:
        t = self.policy.spec(tenant).name
        with self._lock:
            n = self._inflight.get(t, 0) - 1
            if n > 0:
                self._inflight[t] = n
            else:
                self._inflight.pop(t, None)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant view for /stats and the `top` tenants panel."""
        with self._lock:
            tenants = (set(self._inflight) | set(self.admitted)
                       | set(self.throttled))
            out = {}
            for t in sorted(tenants):
                spec = self.policy.spec(t)
                out[t] = {
                    "inflight": self._inflight.get(t, 0),
                    "admitted": self.admitted.get(t, 0),
                    "throttled": self.throttled.get(t, 0),
                    "priority": spec.priority,
                    "weight": spec.qos_weight,
                }
            return out


# ---------------------------------------------------------------------
# Weighted-fair queue (deficit round robin over priority-class lanes)
# ---------------------------------------------------------------------


def _default_classify(item: Any) -> int:
    return int(getattr(item, "qos_class", PRIORITY_CLASSES[DEFAULT_CLASS]))


def _default_weight(item: Any) -> float:
    return float(getattr(item, "qos_weight",
                         DEFAULT_WEIGHTS[DEFAULT_CLASS]))


def _default_cost(item: Any) -> float:
    tokens = getattr(item, "tokens", None)
    size = getattr(tokens, "size", None)
    if size is None:
        size = len(tokens) if tokens is not None else 0
    return float(size) + float(getattr(item, "max_new", 0))


class WeightedFairQueue:
    """Deficit-round-robin pending queue, API-compatible with the
    deque the engine used (append/appendleft/popleft/pop/remove/clear/
    len/iter/bool), so every existing caller — admission fill, cancel,
    abort_all, the migration importer's submit-then-pop — works
    unmodified.

    Lanes are priority classes (lower class drains first when deficits
    tie by construction: the rotation starts each round at the best
    class). Each lane visit adds `quantum x head-item weight` to the
    lane's deficit; a head whose token cost fits the deficit is served
    and the pointer stays on the lane. One lane in play = plain FIFO.

    `appendleft` is the admission path's put-back (PoolExhausted):
    returned items are handed back before any DRR decision, preserving
    the engine's exact retry-first contract. `pop` removes the most
    recently appended item — the migration importer's contract."""

    def __init__(self, quantum: float = 256.0,
                 classify: Callable[[Any], int] = _default_classify,
                 weight: Callable[[Any], float] = _default_weight,
                 cost: Callable[[Any], float] = _default_cost):
        self.quantum = float(quantum)
        self._classify = classify
        self._weight = weight
        self._cost = cost
        self._lanes: Dict[int, List[Tuple[int, Any]]] = {}
        self._deficit: Dict[int, float] = {}
        self._returned: List[Tuple[int, Any]] = []
        self._seq = 0
        self._cursor: Optional[int] = None

    # ---- deque API ---------------------------------------------------

    def append(self, item: Any) -> None:
        self._seq += 1
        self._lanes.setdefault(self._classify(item), []).append(
            (self._seq, item)
        )

    def appendleft(self, item: Any) -> None:
        # Put-backs re-dispense FIFO among themselves (oldest first):
        # the engine only ever puts back the single item it just
        # popped, so insert at the front.
        self._seq += 1
        self._returned.insert(0, (self._seq, item))

    def popleft(self) -> Any:
        if self._returned:
            return self._returned.pop(0)[1]
        lanes = sorted(k for k, v in self._lanes.items() if v)
        if not lanes:
            raise IndexError("pop from an empty WeightedFairQueue")
        if len(lanes) == 1:
            # FIFO degeneracy: no competition, no deficit accounting.
            k = lanes[0]
            entry = self._lanes[k].pop(0)
            self._postpop(k)
            return entry[1]
        # DRR: resume at the cursor lane if it still has deficit
        # standing, else rotate, topping deficits up per visit. Each
        # full rotation adds at least one quantum to every nonempty
        # lane, so the loop always terminates with a serve.
        if self._cursor not in lanes:
            self._cursor = lanes[0]
        start = lanes.index(self._cursor)
        i = start
        while True:
            k = lanes[i % len(lanes)]
            head = self._lanes[k][0][1]
            c = self._cost(head)
            if self._deficit.get(k, 0.0) >= c:
                entry = self._lanes[k].pop(0)
                self._deficit[k] = self._deficit.get(k, 0.0) - c
                self._cursor = k
                self._postpop(k)
                return entry[1]
            # Not enough deficit: top this lane up and move on. The
            # top-up happens on the visit (classic DRR), scaled by the
            # head's weight so heavier tenants accumulate service
            # credit faster.
            self._deficit[k] = (self._deficit.get(k, 0.0)
                                + self.quantum * self._weight(head))
            i += 1

    def pop(self) -> Any:
        """Remove and return the MOST RECENTLY APPENDED item (the
        importer's submit-then-pop contract)."""
        best_k, best_seq = None, -1
        for k, lane in self._lanes.items():
            if lane and lane[-1][0] > best_seq:
                best_k, best_seq = k, lane[-1][0]
        if self._returned and self._returned[-1][0] > best_seq:
            return self._returned.pop()[1]
        if best_k is None:
            raise IndexError("pop from an empty WeightedFairQueue")
        entry = self._lanes[best_k].pop()
        self._postpop(best_k)
        return entry[1]

    def remove(self, item: Any) -> None:
        for lane in ([self._returned]
                     + [self._lanes[k] for k in list(self._lanes)]):
            for i, (_, it) in enumerate(lane):
                if it is item or it == item:
                    del lane[i]
                    self._prune()
                    return
        raise ValueError("WeightedFairQueue.remove(x): x not in queue")

    def clear(self) -> None:
        self._lanes.clear()
        self._deficit.clear()
        self._returned.clear()
        self._cursor = None

    def __len__(self) -> int:
        return (len(self._returned)
                + sum(len(v) for v in self._lanes.values()))

    def __bool__(self) -> bool:
        return len(self) > 0

    def __iter__(self) -> Iterator[Any]:
        for _, item in list(self._returned):
            yield item
        for k in sorted(self._lanes):
            for _, item in list(self._lanes[k]):
                yield item

    # ---- QoS extras --------------------------------------------------

    def _postpop(self, k: int) -> None:
        if not self._lanes.get(k):
            # Standard DRR: an emptied lane forfeits its deficit (an
            # idle class must not bank credit against future rounds).
            self._deficit.pop(k, None)
            self._prune()

    def _prune(self) -> None:
        for k in [k for k, v in self._lanes.items() if not v]:
            del self._lanes[k]
            self._deficit.pop(k, None)
        if self._cursor is not None and self._cursor not in self._lanes:
            self._cursor = None

    def best_waiting(self) -> Optional[Tuple[int, Any]]:
        """(class, head item) of the best-priority nonempty lane —
        the preemption driver's 'who is being starved' probe. Put-back
        items count as their own class."""
        best: Optional[Tuple[int, Any]] = None
        if self._returned:
            item = self._returned[0][1]
            best = (self._classify(item), item)
        for k in sorted(self._lanes):
            if self._lanes[k] and (best is None or k < best[0]):
                best = (k, self._lanes[k][0][1])
                break
        return best

    def depths(self) -> Dict[int, int]:
        """Waiting count per class (put-backs attributed to their own
        class) — the /stats scheduling snapshot."""
        d: Dict[int, int] = {}
        for _, item in self._returned:
            k = self._classify(item)
            d[k] = d.get(k, 0) + 1
        for k, lane in self._lanes.items():
            if lane:
                d[k] = d.get(k, 0) + len(lane)
        return d
