"""Shared prompt-prefix hashing: the one helper both layers key by.

The tier's affinity router and the engine's paged prefix cache both
derive identity from the leading prompt content, and before the KV
fabric each carried a private copy (tier.py hashed the leading 64
tokens / 256 chars into an affinity key; PagedBackend chained
per-block content digests) — close enough to collude, far enough to
drift. The fabric's prefix directory requires them to key IDENTICALLY:
the tier matches a prompt's chain hashes against block hashes reported
by replicas over `GET /kv/prefixes`, so a digest computed tier-side
must be byte-equal to the digest the replica registered for the same
tokens. This module is that single source of truth; `tier.py` and
`cache/paged.py` import it instead of carrying copies that could
disagree.
"""

from __future__ import annotations

import hashlib
from typing import Any, List, Tuple

import numpy as np

#: Affinity keys hash a bounded prompt head so unbounded prompts cost
#: O(1): leading tokens for token payloads, leading characters for
#: text payloads (~4 chars/token heuristic for the estimate).
AFFINITY_HEAD_TOKENS = 64
AFFINITY_HEAD_CHARS = 256


def chain_hashes(tokens: Any, block_size: int) -> List[bytes]:
    """Position-dependent content hashes of the full token blocks:
    h_j = H(h_{j-1} || block_j), so a block only matches when its
    entire prefix matches too (and therefore occupies the same
    absolute positions — required for RoPE'd cached K).

    Tokens are canonicalized to contiguous int32 before hashing: the
    tier hashes Python lists straight off a JSON payload while the
    engine hashes its admission-time arrays, and the digests must be
    byte-equal across that representation gap.
    """
    arr = np.ascontiguousarray(np.asarray(tokens, dtype=np.int32))
    out: List[bytes] = []
    h = b""
    for j in range(arr.size // block_size):
        h = hashlib.blake2b(
            h + arr[j * block_size:(j + 1) * block_size].tobytes(),
            digest_size=16,
        ).digest()
        out.append(h)
    return out


def affinity_head(prefix: Any) -> Tuple[str, int]:
    """(bounded head string, estimated prefix tokens) for a prompt —
    a list of token ids or a text string. The head is what the
    affinity key hashes; the estimate scales how much load imbalance
    an affinity hit is worth in the router's spill decision."""
    if isinstance(prefix, list):
        return (
            ",".join(str(t) for t in prefix[:AFFINITY_HEAD_TOKENS]),
            len(prefix),
        )
    s = str(prefix)
    return s[:AFFINITY_HEAD_CHARS], max(1, len(s) // 4)


def affinity_hash(head: str) -> str:
    """Stable 8-byte digest of an affinity head, prefixed so key
    provenance ('p:' prompt-derived vs 's:' session-pinned) survives
    into logs and the rendezvous ring."""
    return "p:" + hashlib.blake2b(
        head.encode(), digest_size=8
    ).hexdigest()
