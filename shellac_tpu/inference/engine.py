"""Autoregressive generation engine.

Prefill and decode are two jitted programs over the same cached forward:
prefill consumes the whole (padded) prompt in one MXU-friendly pass;
decode runs a `lax.scan` of single-token steps, keeping the loop on
device — no host round-trip per token.

With a `mesh`, the engine runs sharded (tensor-parallel weights, KV
cache sharded over kv_heads, batch over dp/fsdp): pass params already
placed with `shard_params`, and prefill pins the cache's shardings so
the decode scan stays partitioned instead of letting GSPMD re-derive a
layout per step.
"""

from __future__ import annotations

import functools
from typing import Optional

import flax.struct
import jax
import jax.numpy as jnp

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.kvcache import init_cache_for
from shellac_tpu.models import transformer
from shellac_tpu.ops.sampling import sample
from shellac_tpu.parallel.sharding import make_shardings, shard_pytree


@flax.struct.dataclass
class GenerationResult:
    tokens: jax.Array  # (B, max_new_tokens) int32
    logprobs: jax.Array  # (B, max_new_tokens) fp32 — logprob of each sampled token


def shard_params(cfg: ModelConfig, params, mesh):
    """Place inference params onto a mesh by their logical axes.

    Handles both plain and int8-quantized (QTensor) parameter trees.
    """
    from shellac_tpu.ops.quant import QTensor, quantize_logical_axes

    axes = transformer.logical_axes(cfg)
    layers = params["layers"]
    stacks = (list(layers.values())
              if transformer.is_grouped_layers(layers) else [layers])
    q_targets = tuple(sorted({
        k for st in stacks for k, v in st.items() if isinstance(v, QTensor)
    }))
    if q_targets:
        axes = quantize_logical_axes(axes, q_targets)
    return shard_pytree(params, mesh, axes)


class Engine:
    """Holds jitted prefill/decode for one (config, shapes) pair."""

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        *,
        max_len: Optional[int] = None,
        temperature: float = 1.0,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        min_p: Optional[float] = None,
        repetition_penalty: float = 1.0,
        mesh=None,
        kv_quant: Optional[str] = None,
        rolling_window: bool = False,
    ):
        if kv_quant not in (None, "int8"):
            raise ValueError(f"kv_quant={kv_quant!r}; have None, 'int8'")
        if rolling_window and cfg.attn_window is None:
            raise ValueError(
                "rolling_window needs a sliding-window model (attn_window)"
            )
        self.cfg = cfg
        self.params = params
        self.mesh = mesh
        self.kv_quant = kv_quant
        self.rolling_window = rolling_window
        self.max_len = max_len or cfg.max_seq_len
        self.repetition_penalty = repetition_penalty
        self._sampler = functools.partial(
            sample, temperature=temperature, top_k=top_k, top_p=top_p,
            min_p=min_p,
        )
        if mesh is None:
            # Nothing donatable: prefill allocates its cache internally
            # and params must stay live for decode/beam afterwards.
            self._prefill = jax.jit(self._prefill_impl)  # shellac: ignore[SH001]
        else:
            # Pin the cache layout at the prefill boundary; decode then
            # inherits it from its (committed) cache argument.
            from shellac_tpu.inference.kvcache import (
                cache_logical_axes_for,
            )

            axes = cache_logical_axes_for(
                cfg, kv_quant, rolling=rolling_window
            )
            cache_sh = make_shardings(mesh, axes)
            # Nothing donatable here either (see the unsharded branch).
            self._prefill = jax.jit(  # shellac: ignore[SH001]
                self._prefill_impl, out_shardings=(None, cache_sh, None)
            )
        # No donation: the scanned decode returns only tokens/logprobs
        # (the final cache is a discarded scan carry), so there is no
        # output to alias the cache into — donating would just emit
        # XLA's "donated buffers were not usable" warning every compile
        # while invalidating the caller's array for nothing.
        self._decode = jax.jit(  # shellac: ignore[SH001]
            self._decode_impl, static_argnums=(3,)
        )
        self._beam = jax.jit(self._beam_impl, static_argnums=(3, 4, 5))

    def _prefill_impl(self, params, tokens, prompt_len):
        """tokens: (B, S_pad) right-padded; prompt_len: (B,) real lengths."""
        b, s = tokens.shape
        cache = init_cache_for(self.cfg, b, self.max_len, self.kv_quant,
                               rolling=self.rolling_window)
        logits, cache = transformer.forward_with_cache(
            self.cfg, params, tokens, cache, new_tokens_len=prompt_len,
            mesh=self.mesh, fresh_cache=True, attn_impl="auto",
        )
        # Logits at the last *real* prompt position seed the first sample.
        last = jnp.take_along_axis(
            logits, (prompt_len - 1)[:, None, None].astype(jnp.int32), axis=1
        )[:, 0]
        # Token-presence mask over the valid prompt (repetition penalty).
        valid = (
            jnp.arange(s, dtype=jnp.int32)[None, :] < prompt_len[:, None]
        )
        seen = jnp.zeros((b, self.cfg.vocab_size), bool)
        seen = seen.at[jnp.arange(b)[:, None], tokens].max(valid)
        return last, cache, seen

    def _decode_impl(self, params, first_token_logits, cache, steps, key, seen):
        from shellac_tpu.ops.sampling import repetition_penalty

        rp = self.repetition_penalty
        b = first_token_logits.shape[0]
        rows = jnp.arange(b)

        def step(carry, _):
            cache, tok, key, seen = carry
            logits, cache = transformer.forward_with_cache(
                self.cfg, params, tok[:, None], cache, mesh=self.mesh
            )
            logits = repetition_penalty(logits[:, 0], seen, rp)
            key, sub = jax.random.split(key)
            nxt = self._sampler(sub, logits)
            seen = seen.at[rows, nxt].set(True)
            lp = jnp.take_along_axis(
                jax.nn.log_softmax(logits, axis=-1), nxt[:, None], axis=-1
            )[:, 0]
            return (cache, nxt, key, seen), (nxt, lp)

        key, sub = jax.random.split(key)
        first_token_logits = repetition_penalty(first_token_logits, seen, rp)
        first = self._sampler(sub, first_token_logits)
        seen = seen.at[rows, first].set(True)
        first_lp = jnp.take_along_axis(
            jax.nn.log_softmax(first_token_logits, axis=-1), first[:, None], axis=-1
        )[:, 0]
        # The first token comes from prefill logits; the scan samples the
        # remaining steps-1 (no discarded trailing forward pass).
        _, (toks, lps) = jax.lax.scan(
            step, (cache, first, key, seen), None, length=steps - 1
        )
        tokens = jnp.concatenate([first[None], toks], axis=0)
        logprobs = jnp.concatenate([first_lp[None], lps], axis=0)
        return GenerationResult(
            tokens=jnp.moveaxis(tokens, 0, 1), logprobs=jnp.moveaxis(logprobs, 0, 1)
        )

    def generate(
        self,
        prompt_tokens: jax.Array,  # (B, S) int32, right-padded
        prompt_len: Optional[jax.Array] = None,  # (B,) int32
        *,
        max_new_tokens: int = 32,
        key: Optional[jax.Array] = None,
    ) -> GenerationResult:
        if key is None:
            key = jax.random.PRNGKey(0)
        b, s = prompt_tokens.shape
        if prompt_len is None:
            prompt_len = jnp.full((b,), s, jnp.int32)
        first_logits, cache, seen = self._prefill(
            self.params, prompt_tokens, prompt_len
        )
        return self._decode(
            self.params, first_logits, cache, max_new_tokens, key, seen
        )

    # ---- beam search -------------------------------------------------

    @staticmethod
    def _reorder_cache(cache, idx):
        """Gather cache rows by beam index. Every cache field is
        stacked (L, B, ...) except the per-sequence lengths (B,) — so
        the gather axis is a field-name rule, valid for the dense,
        int8, and rolling cache types alike."""
        fields = {
            name: jnp.take(getattr(cache, name), idx, axis=1)
            for name in cache.__dataclass_fields__
            if name != "lengths"
        }
        return cache.replace(lengths=cache.lengths[idx], **fields)

    def _beam_impl(self, params, first_logits, cache, steps, eos_id,
                   length_penalty, ctrans=None):
        """Device-side beam loop: one forward per step for all beams,
        flat top-k over (K, V) candidates, cache rows gathered by the
        winning beams (the standard public algorithm, built on the same
        scanned cached forward as sampling). The expansion/bookkeeping
        math lives in the shared beam_* helpers below so the paged
        engine's CoW beam cannot drift from this one. `ctrans` (a
        TokenDFA table) constrains the search: each beam's logprobs
        are masked through its own DFA row before scoring and the
        per-beam state rides the reorder with the beam."""
        k, _ = first_logits.shape
        scores, beam0, tok0, cstate0 = beam_first_expand(
            first_logits[0], k, ctrans, eos_id
        )
        cache = self._reorder_cache(cache, beam0)
        finished0 = (tok0 == eos_id) if eos_id is not None else (
            jnp.zeros((k,), bool)
        )
        out0 = jnp.zeros((k, steps), jnp.int32).at[:, 0].set(tok0)
        lens0 = jnp.ones((k,), jnp.int32)

        def step(carry, _):
            cache, cur, scores, finished, out, lens, cstate, i = carry
            logits, cache = transformer.forward_with_cache(
                self.cfg, params, cur[:, None], cache, mesh=self.mesh
            )
            (scores, beam, tok, out, lens, finished, was_done,
             cstate) = beam_expand(
                logits[:, 0], scores, finished, out, lens, i, eos_id,
                ctrans, cstate,
            )
            cache = self._reorder_cache(cache, beam)
            # A frozen beam must not grow its cache: re-feeding EOS
            # writes a row, but lengths were already advanced by the
            # forward — roll them back for finished beams.
            cache = cache.replace(
                lengths=jnp.where(
                    was_done, cache.lengths - 1, cache.lengths
                )
            )
            return (cache, tok, scores, finished, out, lens, cstate,
                    i + 1), None

        carry = (cache, tok0, scores, finished0, out0, lens0, cstate0,
                 jnp.int32(1))
        (cache, _, scores, finished, out, lens, _, _), _ = jax.lax.scan(
            step, carry, None, length=steps - 1
        )
        return beam_rank(scores, out, lens, length_penalty)

    def beam_search(
        self,
        prompt_tokens,  # (S,) or (1, S) int32
        *,
        num_beams: int = 4,
        max_new_tokens: int = 32,
        eos_id: Optional[int] = None,
        length_penalty: float = 1.0,
        constraint=None,
    ):
        """Deterministic beam decode of ONE prompt.

        Returns (sequences, scores): sequences is a list of up to
        num_beams token lists (EOS included when hit, best first),
        scores their length-penalized log-probabilities. With a
        compiled `constraint` (constraints.TokenDFA), every beam's
        candidates are masked through its own DFA state before scoring
        — each returned sequence satisfies the grammar — and beams
        forced onto masked candidates (fewer legal continuations than
        beams) are pruned from the result, so fewer than num_beams
        sequences may return. The dense/int8/rolling caches gather
        rows directly; for block pools use
        PagedBatchingEngine.beam_search, which reorders via
        copy-on-write block tables and returns bit-identical beams.
        """
        if num_beams < 1:
            raise ValueError("num_beams must be >= 1")
        if max_new_tokens < 1:
            raise ValueError("max_new_tokens must be >= 1")
        ctrans, eos_id = check_beam_constraint(
            constraint, eos_id, self.cfg.vocab_size
        )
        tokens = jnp.asarray(prompt_tokens, jnp.int32).reshape(1, -1)
        s = tokens.shape[1]
        if s + max_new_tokens + 1 > self.max_len:
            raise ValueError(
                f"prompt {s} + max_new {max_new_tokens} exceeds "
                f"max_len {self.max_len}"
            )
        # Prefill ONCE (B=1): every beam starts from the same prompt,
        # so the K-way cache is a broadcast of one row, not K prefills.
        first_logits, cache, _ = self._prefill(
            self.params, tokens, jnp.full((1,), s, jnp.int32)
        )
        first_logits = jnp.tile(first_logits, (num_beams, 1))
        cache = self._reorder_cache(
            cache, jnp.zeros((num_beams,), jnp.int32)
        )
        out, norm, lens = self._beam(
            self.params, first_logits, cache, int(max_new_tokens),
            eos_id, float(length_penalty), ctrans,
        )
        out, norm, lens = jax.device_get((out, norm, lens))
        return beam_filter_invalid(out, norm, lens)


#: Junk-beam score: a beam forced onto a constraint-masked candidate
#: (fewer legal continuations than beams) carries this; the host-side
#: BEAM_INVALID filter drops it from the returned set.
BEAM_NEG = jnp.float32(-1e30)
BEAM_INVALID = -1e20  # host-side validity threshold on final scores


def _beam_mask(lp, row, eos_id):
    """Mask a (K, V) logprob block by each beam's DFA row ((K, V+1);
    -1 = disallowed, last column = EOS legality). Disallowed entries
    drop to BEAM_NEG so a flat top-k can only pick them when fewer
    than K legal candidates exist — those beams rank (and are pruned)
    as invalid."""
    allowed = row[:, :-1] >= 0
    if eos_id is not None:
        allowed = allowed.at[:, eos_id].set(row[:, -1] >= 0)
    return jnp.where(allowed, lp, BEAM_NEG)


def _beam_advance_state(row, cstate, tok, keep, eos_id):
    """Advance each beam's DFA state past its selected token (`row` is
    the pre-selection (K, V+1) table rows, already gathered by beam).
    `keep` marks beams whose state must not move (frozen EOS
    self-loops). Clipped at 0 so an invalid (masked-candidate) beam
    stays traversable — its BEAM_NEG score already prunes it."""
    col = tok
    if eos_id is not None:
        col = jnp.where(tok == eos_id, row.shape[1] - 1, tok)
    nxt = jnp.take_along_axis(row, col[:, None], axis=1)[:, 0]
    return jnp.where(keep, cstate, jnp.maximum(nxt, 0))


def beam_first_expand(last_logits, k, ctrans=None, eos_id=None):
    """First beam expansion from ONE distribution (every beam holds the
    same prefill): masking all but beam 0 keeps the flat top-k from
    picking duplicate (beam, token) pairs. last_logits: (V,). With a
    constraint table `ctrans`, the DFA's start row masks the
    distribution and the returned per-beam states advance past each
    selected token. Returns (scores, beam0, tok0, cstate0), each
    (k,)."""
    lp0 = jax.nn.log_softmax(last_logits.astype(jnp.float32))
    v = lp0.shape[0]
    if ctrans is not None:
        lp0 = _beam_mask(lp0[None], ctrans[:1], eos_id)[0]
    scores0 = jnp.where(jnp.arange(k) == 0, 0.0, BEAM_NEG)
    cand = (scores0[:, None] + lp0[None, :]).reshape(-1)
    scores, flat = jax.lax.top_k(cand, k)
    tok0 = (flat % v).astype(jnp.int32)
    cstate0 = jnp.zeros((k,), jnp.int32)
    if ctrans is not None:
        row = jnp.broadcast_to(ctrans[0][None], (k, ctrans.shape[1]))
        cstate0 = _beam_advance_state(
            row, cstate0, tok0, jnp.zeros((k,), bool), eos_id
        )
    return scores, flat // v, tok0, cstate0


def beam_expand(logits, scores, finished, out, lens, i, eos_id,
                ctrans=None, cstate=None):
    """One beam-search expansion: frozen-EOS self-loop, flat top-k over
    (K, V) candidates, and the out/lens/finished bookkeeping — SHARED
    by the dense loop (Engine._beam_impl) and the paged CoW loop
    (PagedBatchingEngine._beam_paged_impl) so their beams cannot
    drift. With (ctrans, cstate) each live beam's logprobs are masked
    by its own DFA row BEFORE scoring and the returned cstate advanced
    with the beam reorder (frozen beams keep the EOS self-loop
    regardless — they terminated in an accepting state). Returns
    (scores, beam, tok, out, lens, finished, was_done, cstate); the
    caller owns the cache reorder and length rollback."""
    k = scores.shape[0]
    v = logits.shape[-1]
    lp = jax.nn.log_softmax(logits.astype(jnp.float32))
    row = None
    if ctrans is not None:
        row = ctrans[cstate]  # (K, V+1)
        lp = _beam_mask(lp, row, eos_id)
    if eos_id is not None:
        # Finished beams persist unchanged: their only legal
        # continuation is a zero-cost EOS self-loop (this wins over
        # the constraint mask — the beam already terminated legally).
        frozen = jnp.full((v,), BEAM_NEG).at[eos_id].set(0.0)
        lp = jnp.where(finished[:, None], frozen[None], lp)
    cand = (scores[:, None] + lp).reshape(-1)
    scores, flat = jax.lax.top_k(cand, k)
    beam = flat // v
    tok = (flat % v).astype(jnp.int32)
    out = out[beam].at[:, i].set(tok)
    was_done = finished[beam]
    lens = jnp.where(was_done, lens[beam], lens[beam] + 1)
    if eos_id is not None:
        finished = was_done | (tok == eos_id)
    else:
        finished = was_done
    if ctrans is not None:
        cstate = _beam_advance_state(
            row[beam], cstate[beam], tok, was_done, eos_id
        )
    elif cstate is not None:
        cstate = cstate[beam]
    return scores, beam, tok, out, lens, finished, was_done, cstate


def beam_rank(scores, out, lens, length_penalty):
    """Length-penalized final ranking (HF/GNMT convention: divide by
    len^alpha; alpha=0 is raw sum-logprob, alpha=1 is mean)."""
    norm = scores / jnp.power(lens.astype(jnp.float32),
                              jnp.float32(length_penalty))
    order = jnp.argsort(-norm)
    return out[order], norm[order], lens[order]


def check_beam_constraint(constraint, eos_id, vocab_size):
    """Validate a beam-search constraint and resolve the EOS id the
    search must use. Returns (ctrans device array or None, eos_id) —
    the same submit-time contract the batching engine enforces:
    termination (EOS finishing a beam) and the DFA's EOS column must
    agree, or the mask would silently diverge from the search."""
    if constraint is None:
        return None, eos_id
    from shellac_tpu.inference.constraints import TokenDFA

    if not isinstance(constraint, TokenDFA):
        raise ValueError(
            "beam constraint must be a compiled constraints.TokenDFA "
            "(the server compiles specs; library users call "
            "compile_token_dfa)"
        )
    if constraint.trans.shape[1] != vocab_size + 1:
        raise ValueError(
            f"beam constraint table covers "
            f"{constraint.trans.shape[1] - 1} tokens, model vocab is "
            f"{vocab_size}"
        )
    if eos_id is None:
        eos_id = constraint.eos_id
    elif eos_id != constraint.eos_id:
        raise ValueError(
            f"beam constraint eos_id {constraint.eos_id} must equal "
            f"the requested eos_id {eos_id} (termination and EOS "
            "masking must agree)"
        )
    if not 0 <= eos_id < vocab_size:
        # jnp .at[] clips an out-of-range index instead of raising, so
        # an EOS the model cannot emit would silently corrupt another
        # token's mask AND leave every beam unable to terminate-accept.
        raise ValueError(
            f"constraint eos_id {eos_id} is outside the model vocab "
            f"({vocab_size}); the model cannot emit it"
        )
    return jnp.asarray(constraint.trans), eos_id


def beam_filter_invalid(out, norm, lens):
    """Host-side post-pass shared by the dense and paged searches:
    drop beams whose score shows they were forced onto a masked
    candidate (a constrained search with fewer legal continuations
    than beams). The best beam always survives — the compiled DFA has
    no dead states, so a legal path exists whenever the grammar is
    non-empty."""
    seqs, scores = [], []
    for row, n, s in zip(out, lens, norm):
        if float(s) <= BEAM_INVALID:
            continue
        seqs.append(row[:n].tolist())
        scores.append(float(s))
    return seqs, scores


def truncate_at_stop(tokens, stop, prompt_outputs=None):
    """Host-side stop-sequence post-processing for Engine outputs.

    The Engine's decode loop runs entirely on device (a lax.scan with a
    fixed budget), so stop sequences are applied after the fact: each
    row of `tokens` (B, max_new) is cut at the FIRST occurrence of any
    stop sequence, excluding the match. Returns a list of per-row
    python lists (ragged). The continuous-batching engine implements
    the same contract with true early exit (its submit(..., stop=...));
    this helper keeps the single-request API consistent.
    """
    import numpy as np

    rows = np.asarray(tokens)
    seqs = [list(map(int, s)) for s in stop]
    if any(len(s) == 0 for s in seqs):
        raise ValueError("empty stop sequence")
    out = []
    for row in rows:
        row = row.tolist()
        cut = len(row)
        for s in seqs:
            n = len(s)
            for i in range(0, len(row) - n + 1):
                if row[i:i + n] == s:
                    cut = min(cut, i)
                    break
        out.append(row[:cut])
    return out
