"""Scenario-matrix SLO gate: workload × chaos × SLO assertions.

`bench_decode.py --gate` answers "is the engine still fast";
`python -m shellac_tpu scenarios --gate` answers "does the fleet
still meet its SLOs under realistic load". Each `Scenario` is one
cell of the matrix:

    workload model (inference/workload.py — seeded, deterministic)
  × optional chaos injection (inference/chaos.py — proxy faults,
    replica SIGKILL)
  × per-scenario SLO assertions (obs/slo.py spec grammar, e.g.
    `availability@80`, `e2e<25s@80`, `ttft_p95<20s@80`)

run against a live replica (`--target URL`) or a self-hosted tiny
in-process server (the CI path), producing a schema-checked verdict
row per scenario:

  - `pass` — every SLO's final good fraction met its objective
  - `fail` — an SLO finished below objective; the runner fires a
    PR 13 incident bundle (POST /debug/incident) whose manifest
    names a violating request's trace id, resolvable via
    `/debug/request/<id>`
  - `skip` — the target cannot run the scenario for a NAMED reason:
    spec engines refuse features in `spec_batching.EXCLUSIONS`
    (`excluded: overlap_decode`), or a live target has a required
    flag off (`disabled: overlap_prefill`). Exclusion-matrix
    fallbacks are verdicts, never silent passes — ROADMAP item 5's
    spec-pipeline hole stays visible in the ledger.

The stable projection of the rows (names, verdicts, skip reasons,
SLO spec strings, seeds, workload fingerprints — nothing timed) is
committed to `SCENARIO_LEDGER.json` exactly like BENCH_LEDGER.json:
`--check` detects schema drift (exit 2) and staleness (exit 3)
without running anything, `--gate` runs the fast subset and compares
(exit 1 on any SLO failure), `--update-ledger` rewrites the baseline.
`--induce-violation` swaps every assertion for an impossible one —
the CI self-test that proves the gate can actually fail.

SLIs are measured CLIENT-side from the load generator's captured
result rows (TTFT = first NDJSON delta, e2e = settled wall time,
availability = non-error outcomes; a client cancel counts good — the
user hung up, the fleet did not fail). SLO assertions are restricted
to client-measurable SLIs (`ttft`, `e2e`, `availability`) and a
config using anything else dies at registry build, not mid-run.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from shellac_tpu.inference.chaos import ChaosProxy, LoadGenerator, ReplicaProc
from shellac_tpu.inference.spec_batching import EXCLUSIONS
from shellac_tpu.inference.workload import (
    Burst,
    Diurnal,
    WorkloadConfig,
    WorkloadModel,
)
from shellac_tpu.obs import (
    TRACE_HEADER,
    FlightRecorder,
    Registry,
    ScenarioMetrics,
    SLOEngine,
    format_trace_header,
    parse_slo_specs,
)

LEDGER_SCHEMA = 1
DEFAULT_LEDGER = "SCENARIO_LEDGER.json"

#: SLIs the client-side gate can measure from captured result rows.
#: `tpot` and `queue_wait` are server-internal; asserting them here
#: would silently measure nothing, so the registry refuses them.
GATE_SLIS = ("ttft", "e2e", "availability")

#: Client outcomes that count GOOD for availability: the request was
#: served, or the CLIENT chose to hang up mid-stream.
_GOOD_OUTCOMES = ("ok", "cancelled")

#: The impossible assertion `--induce-violation` swaps in: every
#: served request takes longer than 1us, so the gate MUST fail — the
#: self-test that proves a green gate means something.
INDUCED_SLO = "e2e<1us@99.9"

VERDICTS = ("pass", "fail", "skip")

CHAOS_KINDS = ("unavailable_mid_run", "kill_replica")

#: Self-hosted server profiles (in-process tiny model, the CI path).
#: `long` raises max_len and chunks prefill so the long-tail scenario
#: actually exercises the chunked-prefill admission path.
PROFILES: Dict[str, Dict[str, object]] = {
    "default": {"n_slots": 4, "max_len": 192},
    "long": {"n_slots": 2, "max_len": 640, "prefill_chunk": 64},
}


class SchemaDrift(RuntimeError):
    """The committed ledger no longer matches the verdict-row schema
    this code writes (mirrors scripts/bench_ledger.py)."""


# ---------------------------------------------------------------------
# Scenario definition


@dataclass(frozen=True)
class Scenario:
    """One matrix cell. `validate()` runs at registry build so a bad
    workload config or an unparseable SLO spec fails the import of
    the registry, loudly, before any traffic moves."""

    name: str
    description: str
    workload: WorkloadConfig
    slos: Tuple[str, ...]
    #: Engine features the scenario needs. Names come from the spec
    #: exclusion matrix (`spec_batching.EXCLUSIONS`) plus the overlap
    #: flags /stats exposes — the skip decision is made against them.
    requires: Tuple[str, ...] = ()
    #: Engine profile the scenario runs on: "dense" (the default
    #: overlapped engine) or "spec" (speculative — every `requires`
    #: hit in EXCLUSIONS becomes a named skip).
    engine: str = "dense"
    profile: str = "default"
    chaos: Optional[str] = None
    #: In the fast CI gate subset. gate=False scenarios (subprocess
    #: chaos) run only with --all or an explicit --scenario.
    gate: bool = True

    def validate(self) -> None:
        if not self.name or not self.name.replace("_", "").isalnum():
            raise ValueError(f"bad scenario name {self.name!r}")
        self.workload.validate()
        specs = parse_slo_specs(self.slos)
        if not specs:
            raise ValueError(
                f"scenario {self.name!r} asserts no SLOs — a scenario "
                "without assertions is not a gate"
            )
        for s in specs:
            if s.sli not in GATE_SLIS:
                raise ValueError(
                    f"scenario {self.name!r} SLO {s.name!r}: SLI "
                    f"{s.sli!r} is not client-measurable "
                    f"(gate SLIs: {', '.join(GATE_SLIS)})"
                )
        if self.engine not in ("dense", "spec"):
            raise ValueError(
                f"scenario {self.name!r}: unknown engine "
                f"{self.engine!r} (dense|spec)"
            )
        if self.profile not in PROFILES:
            raise ValueError(
                f"scenario {self.name!r}: unknown profile "
                f"{self.profile!r} (known: {', '.join(PROFILES)})"
            )
        if self.chaos is not None and self.chaos not in CHAOS_KINDS:
            raise ValueError(
                f"scenario {self.name!r}: unknown chaos "
                f"{self.chaos!r} (known: {', '.join(CHAOS_KINDS)})"
            )
        known = set(EXCLUSIONS) | {"overlap_decode", "overlap_prefill"}
        for r in self.requires:
            if r not in known:
                raise ValueError(
                    f"scenario {self.name!r}: unknown required "
                    f"feature {r!r} (known: {', '.join(sorted(known))})"
                )

    def skip_reason(self, stats: Optional[dict] = None
                    ) -> Optional[str]:
        """A NAMED reason this scenario cannot run, or None.

        Static half: a `spec` engine profile refuses every feature in
        the exclusion matrix — `excluded: <key>` (the matrix is the
        contract; tests meta-check it). Live half: a `--target`'s
        /stats engine block showing a speculative class, or a
        required overlap flag off, skips the same way. Never returns
        an empty string: a skip without a name would be a silent
        pass."""
        if self.engine == "spec":
            for r in self.requires:
                if r in EXCLUSIONS:
                    return f"excluded: {r}"
        if stats:
            eng = stats.get("engine") or {}
            if "Speculative" in str(eng.get("class", "")):
                for r in self.requires:
                    if r in EXCLUSIONS:
                        return f"excluded: {r}"
            for r in self.requires:
                if (r in ("overlap_decode", "overlap_prefill")
                        and r in eng and not eng.get(r)):
                    return f"disabled: {r}"
        return None


def _build_scenarios() -> Dict[str, Scenario]:
    """The catalog. Workload configs are CI-scale (seconds of traffic
    against the tiny model); the production-scale shape lives in
    `WorkloadConfig`'s defaults and `docs/scenarios.md`. Objectives
    are deliberately generous — the gate asserts 'the fleet serves
    its traffic', and a flaky gate teaches operators to ignore it."""

    small = dict(
        tenants=("acme", "globex", "initech", "umbrella"),
        prompt_buckets=((4, 16, 0.6), (16, 48, 0.3), (48, 96, 0.1)),
        tail_p=0.0, max_new=(2, 6), diurnal=None, vocab=200,
    )
    scns = [
        Scenario(
            name="steady_mixed",
            description="the full request-type mix at a steady "
                        "open-loop rate — the baseline cell",
            workload=WorkloadConfig(
                seed=11, duration_s=4.0, base_rate=5.0,
                mix={"chat": 0.3, "stream": 0.25, "stream_cancel": 0.1,
                     "tool": 0.15, "prefill_heavy": 0.05,
                     "shared_prefix": 0.15},
                shared_prefix_len=24, **small,
            ),
            slos=("availability@80", "e2e<25s@80"),
            requires=("constraint",),
        ),
        Scenario(
            name="burst_ramp",
            description="a 3x burst riding a diurnal triangle ramp — "
                        "open-loop arrivals do not slow down because "
                        "the server did",
            workload=WorkloadConfig(
                seed=12, duration_s=4.0, base_rate=4.0,
                bursts=(Burst(start_s=1.0, duration_s=1.0,
                              multiplier=3.0),),
                mix={"chat": 0.6, "stream": 0.4},
                **{**small, "diurnal": Diurnal(amplitude=0.5,
                                               period_s=4.0)},
            ),
            slos=("availability@80", "e2e<25s@80"),
        ),
        Scenario(
            name="long_tail_prefill",
            description="prompt-length long tail against chunked "
                        "prefill (tail scaled to CI; production tail "
                        "is 32k+)",
            workload=WorkloadConfig(
                seed=13, duration_s=4.0, base_rate=1.5,
                tenants=("acme", "globex"),
                mix={"prefill_heavy": 0.7, "chat": 0.3},
                prompt_buckets=((16, 64, 0.7), (64, 256, 0.3)),
                tail_p=0.2, tail_len=512, max_new=(1, 2),
                diurnal=None, vocab=200,
            ),
            slos=("availability@70", "e2e<30s@70"),
            profile="long",
        ),
        Scenario(
            name="shared_prefix_reuse",
            description="shared-system-prompt traffic — identical "
                        "prefix hash chains the KV fabric dedups",
            workload=WorkloadConfig(
                seed=14, duration_s=4.0, base_rate=4.0,
                mix={"shared_prefix": 0.8, "chat": 0.2},
                shared_prefix_len=24, **small,
            ),
            slos=("availability@80", "e2e<25s@80"),
        ),
        Scenario(
            name="streaming_cancel",
            description="streaming chats with mid-flight client "
                        "cancellations — hangups are good events, "
                        "not failures",
            workload=WorkloadConfig(
                seed=15, duration_s=4.0, base_rate=4.0,
                mix={"stream": 0.5, "stream_cancel": 0.5},
                **small,
            ),
            slos=("availability@80", "ttft_p95<20s@80"),
        ),
        Scenario(
            name="multi_tenant_zipf",
            description="eight tenants, Zipf popularity — the heavy "
                        "head and the long tail on one engine",
            workload=WorkloadConfig(
                seed=16, duration_s=4.0, base_rate=5.0,
                tenants=("acme", "globex", "initech", "umbrella",
                         "hooli", "wonka", "stark", "tyrell"),
                zipf_s=1.4,
                mix={"chat": 0.5, "stream": 0.5},
                **{k: v for k, v in small.items() if k != "tenants"},
            ),
            slos=("availability@80", "e2e<25s@80"),
        ),
        Scenario(
            name="chaos_unavailable",
            description="the wire goes 503 for the middle third of "
                        "the run (ChaosProxy) — availability degrades "
                        "but must not collapse",
            workload=WorkloadConfig(
                seed=17, duration_s=4.5, base_rate=5.0,
                mix={"chat": 1.0}, **small,
            ),
            slos=("availability@40",),
            chaos="unavailable_mid_run",
        ),
        Scenario(
            name="replica_kill",
            description="SIGKILL a real serve subprocess mid-run — "
                        "the unplanned death under open-loop load "
                        "(subprocess startup: excluded from the fast "
                        "gate)",
            workload=WorkloadConfig(
                seed=18, duration_s=6.0, base_rate=3.0,
                mix={"chat": 1.0}, **small,
            ),
            slos=("availability@20",),
            chaos="kill_replica",
            gate=False,
        ),
        Scenario(
            name="spec_overlap_decode",
            description="mixed load on a speculative engine with the "
                        "decode flight queue — refused by the "
                        "exclusion matrix, recorded as a named skip",
            workload=WorkloadConfig(
                seed=19, duration_s=4.0, base_rate=4.0,
                mix={"chat": 1.0}, **small,
            ),
            slos=("availability@80",),
            engine="spec",
            requires=("overlap_decode",),
        ),
        Scenario(
            name="spec_overlap_prefill",
            description="speculative engine with chunked-prefill "
                        "admission overlap — the other excluded "
                        "pipeline, also a named skip",
            workload=WorkloadConfig(
                seed=20, duration_s=4.0, base_rate=4.0,
                mix={"chat": 1.0}, **small,
            ),
            slos=("availability@80",),
            engine="spec",
            requires=("overlap_prefill",),
        ),
        Scenario(
            name="spec_constrained_tools",
            description="tool/constrained mix on a speculative "
                        "engine — drafts propose unconstrained "
                        "tokens, so the matrix refuses it",
            workload=WorkloadConfig(
                seed=21, duration_s=4.0, base_rate=4.0,
                mix={"tool": 1.0}, **small,
            ),
            slos=("availability@80",),
            engine="spec",
            requires=("constraint",),
        ),
    ]
    out: Dict[str, Scenario] = {}
    for s in scns:
        s.validate()
        if s.name in out:
            raise ValueError(f"duplicate scenario name {s.name!r}")
        out[s.name] = s
    return out


SCENARIOS: Dict[str, Scenario] = _build_scenarios()


# ---------------------------------------------------------------------
# Client-side SLI evaluation


def _measurement(sli: str, row: Mapping) -> Optional[float]:
    """The SLI value one captured result row contributes, or None if
    the row does not participate (e.g. TTFT of a non-streaming
    request, e2e of a request that never completed)."""
    if sli == "ttft":
        return row.get("ttft_s") if row.get("stream") else None
    if sli == "e2e":
        return (row.get("latency_s")
                if row.get("outcome") == "ok" else None)
    return None


def evaluate_slos(specs, results: Sequence[Mapping]
                  ) -> List[Dict[str, object]]:
    """Fold captured result rows into per-SLO verdict entries:
    good/total counts, final good fraction, ok flag, and the trace id
    of the FIRST violating request (the incident exemplar). An SLO
    that measured zero events is a failure — asserting against no
    data must be loud, never a vacuous pass."""
    out = []
    for spec in specs:
        good = total = 0
        violating: Optional[str] = None
        for row in results:
            if spec.sli == "availability":
                if row.get("outcome") == "client_saturated":
                    # The CLIENT ran out of capacity; counted in the
                    # outcome tally, excluded from the server's SLI.
                    continue
                total += 1
                if row.get("outcome") in _GOOD_OUTCOMES:
                    good += 1
                elif violating is None:
                    violating = row.get("trace_id")
            else:
                v = _measurement(spec.sli, row)
                if v is None:
                    continue
                total += 1
                if v <= spec.threshold_s:
                    good += 1
                elif violating is None:
                    violating = row.get("trace_id")
        frac = (good / total) if total else None
        ok = total > 0 and frac >= spec.objective
        out.append({
            "slo": spec.name,
            "objective": spec.objective,
            "good": good,
            "total": total,
            "good_fraction": (round(frac, 6)
                              if frac is not None else None),
            "ok": bool(ok),
            "violating_trace": None if ok else violating,
        })
    return out


# ---------------------------------------------------------------------
# Verdict rows + ledger

_ROW_KEYS = ("schema", "scenario", "description", "verdict",
             "skip_reason", "engine", "chaos", "requires", "slos",
             "seed", "workload_fingerprint", "gate")


def stable_row(row: Mapping) -> Dict[str, object]:
    """The run-stable projection committed to the ledger: no counts,
    no latencies, no trace ids — only what a config change or a
    verdict flip would move."""
    slos = row["slos"]
    if slos and isinstance(slos[0], Mapping):
        slos = [e["slo"] for e in slos]
    return {
        "schema": row["schema"],
        "scenario": row["scenario"],
        "description": row["description"],
        "verdict": row["verdict"],
        "skip_reason": row["skip_reason"],
        "engine": row["engine"],
        "chaos": row["chaos"],
        "requires": list(row["requires"]),
        "slos": list(slos),
        "seed": row["seed"],
        "workload_fingerprint": row["workload_fingerprint"],
        "gate": row["gate"],
    }


def check_row(row: Mapping, committed: bool = True) -> None:
    """Schema-check one verdict row; raises SchemaDrift naming every
    problem (unknown shapes must fail loudly, not flow onward).
    `committed=True` additionally refuses a 'fail' verdict — a
    committed baseline that fails is not a baseline; live runner
    output (committed=False) may of course fail."""
    problems = []
    for k in _ROW_KEYS:
        if k not in row:
            problems.append(f"missing key {k!r}")
    if problems:
        raise SchemaDrift(
            f"ledger row {row.get('scenario', '?')!r}: "
            + "; ".join(problems)
        )
    if row["schema"] != LEDGER_SCHEMA:
        problems.append(
            f"schema {row['schema']!r} != {LEDGER_SCHEMA}")
    if row["verdict"] not in VERDICTS:
        problems.append(f"verdict {row['verdict']!r} not in {VERDICTS}")
    if (row["verdict"] == "skip") != bool(row["skip_reason"]):
        problems.append(
            "skip_reason must be set exactly when verdict == 'skip' "
            "(a skip without a name is a silent pass)"
        )
    if committed and row["verdict"] == "fail":
        problems.append(
            "committed ledger carries verdict 'fail' — a baseline "
            "that fails is not a baseline"
        )
    if not isinstance(row["slos"], list) or not row["slos"]:
        problems.append("slos must be a non-empty list")
    else:
        for e in row["slos"]:
            name = e["slo"] if isinstance(e, Mapping) else e
            if not isinstance(name, str) or "@" not in name:
                problems.append(f"bad SLO entry {e!r}")
    if not isinstance(row["workload_fingerprint"], str) \
            or len(row["workload_fingerprint"]) != 64:
        problems.append("workload_fingerprint must be a sha256 hex")
    if problems:
        raise SchemaDrift(
            f"ledger row {row['scenario']!r}: " + "; ".join(problems))


def check_ledger(doc: Mapping) -> None:
    if not isinstance(doc, Mapping):
        raise SchemaDrift("ledger is not a JSON object")
    if doc.get("schema") != LEDGER_SCHEMA:
        raise SchemaDrift(
            f"ledger schema {doc.get('schema')!r} != {LEDGER_SCHEMA}")
    rows = doc.get("scenarios")
    if not isinstance(rows, list) or not rows:
        raise SchemaDrift("ledger has no scenarios list")
    seen = set()
    for row in rows:
        check_row(row)
        if row["scenario"] in seen:
            raise SchemaDrift(
                f"duplicate ledger row {row['scenario']!r}")
        seen.add(row["scenario"])


def expected_static_rows(scenarios: Sequence[Scenario]
                         ) -> List[Dict[str, object]]:
    """What the ledger MUST contain, computable without running
    anything: every field but the verdict is a pure function of the
    scenario config (the workload fingerprint hashes the generated
    schedule, no server needed), and skip verdicts are statically
    known from the exclusion matrix."""
    out = []
    for s in scenarios:
        skip = s.skip_reason()
        out.append({
            "schema": LEDGER_SCHEMA,
            "scenario": s.name,
            "description": s.description,
            "verdict": "skip" if skip else None,  # None: needs a run
            "skip_reason": skip,
            "engine": s.engine,
            "chaos": s.chaos,
            "requires": list(s.requires),
            "slos": list(s.slos),
            "seed": s.workload.seed,
            "workload_fingerprint": WorkloadModel(
                s.workload).fingerprint(),
            "gate": s.gate,
        })
    return out


def compare_to_ledger(rows: Sequence[Mapping], doc: Mapping,
                      verdict_known: bool) -> List[str]:
    """Diff run/static rows against the committed ledger; returns
    human-readable mismatch lines (empty = in sync). With
    verdict_known=False (the no-run --check path) verdicts are only
    compared for statically-known skips."""
    committed = {r["scenario"]: r for r in doc.get("scenarios", [])}
    fresh = {r["scenario"]: r for r in rows}
    lines = []
    for name in sorted(set(committed) | set(fresh)):
        if name not in committed:
            lines.append(f"{name}: missing from committed ledger")
            continue
        if name not in fresh:
            lines.append(f"{name}: committed but no longer in the "
                         "gate set")
            continue
        a, b = fresh[name], committed[name]
        for k in _ROW_KEYS:
            if k == "verdict" and not verdict_known \
                    and a.get("verdict") is None:
                continue
            av = a.get(k)
            bv = b.get(k)
            if isinstance(av, tuple):
                av = list(av)
            if av != bv:
                lines.append(f"{name}: {k} changed "
                             f"(ran={av!r} committed={bv!r})")
    return lines


def write_ledger(path: str, rows: Sequence[Mapping]) -> None:
    doc = {
        "schema": LEDGER_SCHEMA,
        "note": "committed scenario-gate baseline; regenerate with "
                "`python -m shellac_tpu scenarios --update-ledger`",
        "scenarios": [stable_row(r) for r in
                      sorted(rows, key=lambda r: r["scenario"])],
    }
    with open(path, "w") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")


def load_ledger(path: str) -> Mapping:
    try:
        with open(path) as f:
            return json.load(f)
    except OSError as e:
        raise SchemaDrift(f"cannot read ledger {path}: {e}")
    except ValueError as e:
        raise SchemaDrift(f"ledger {path} is not valid JSON: {e}")


# ---------------------------------------------------------------------
# The runner


class _Hosted:
    """One self-hosted in-process replica (profile-keyed)."""

    def __init__(self, profile: str, registry, recorder,
                 incident_dir: Optional[str]):
        import jax

        from shellac_tpu import get_model_config
        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )
        from shellac_tpu.models import transformer
        from shellac_tpu.training.tokenizer import ByteTokenizer

        # vocab_size 259 covers the ByteTokenizer specials so the
        # constrained-decode (tool) kind has a real eos_id to stop at.
        cfg = get_model_config("tiny").replace(dtype="float32",
                                               vocab_size=259)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        self.server = InferenceServer(
            cfg, params, tokenizer=ByteTokenizer(), temperature=0.0,
            registry=registry, recorder=recorder,
            incident_dir=incident_dir, eos_id=ByteTokenizer.EOS,
            **PROFILES[profile],
        )
        self.httpd = make_http_server(self.server)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        self.warmed_len = 0

    def close(self) -> None:
        self.httpd.shutdown()
        self.server.close()


def _http_json(url: str, payload: Optional[dict] = None,
               headers: Optional[dict] = None,
               timeout: float = 30.0) -> Tuple[int, dict]:
    hdrs = {"Content-Type": "application/json", **(headers or {})}
    req = urllib.request.Request(
        url, data=(json.dumps(payload).encode()
                   if payload is not None else None),
        headers=hdrs,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = r.read()
            try:
                return r.status, json.loads(body or b"{}")
            except ValueError:
                # NDJSON (a drained warmup stream) or non-JSON body.
                return r.status, {}
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read() or b"{}")
        except ValueError:
            body = {}
        return e.code, body
    except (OSError, urllib.error.URLError) as e:
        return 0, {"error": repr(e)}


class ScenarioRunner:
    """Run scenarios against `--target URL` or self-hosted tiny
    replicas, producing full verdict rows. Owns one registry +
    flight recorder — scenario lifecycle events and (when
    self-hosting) the replica's own events land in ONE timeline, so
    `/debug/request/<violating-trace>` resolves against the same
    recorder the incident bundle snapshots."""

    def __init__(self, *, target: Optional[str] = None,
                 incident_dir: Optional[str] = None,
                 registry: Optional[Registry] = None,
                 recorder: Optional[FlightRecorder] = None,
                 timeout: float = 30.0,
                 duration_scale: float = 1.0,
                 seed: Optional[int] = None,
                 induce_violation: bool = False,
                 max_in_flight: int = 64,
                 log=print):
        self.target = target.rstrip("/") if target else None
        self.incident_dir = incident_dir
        self.registry = registry if registry is not None else Registry()
        self.recorder = (recorder if recorder is not None
                         else FlightRecorder(registry=self.registry))
        self.metrics = ScenarioMetrics(self.registry)
        self.timeout = timeout
        self.duration_scale = duration_scale
        self.seed = seed
        self.induce = induce_violation
        self.max_in_flight = max_in_flight
        self.log = log
        self._hosted: Dict[str, _Hosted] = {}
        self._target_stats: Optional[dict] = None

    # ---- targets ----------------------------------------------------

    def close(self) -> None:
        for h in self._hosted.values():
            h.close()
        self._hosted.clear()

    def _stats_for(self, url: str) -> dict:
        status, body = _http_json(url + "/stats", timeout=10.0)
        return body if status == 200 else {}

    def _resolve_target(self, scenario: Scenario) -> Tuple[str, dict]:
        """(base_url, /stats body) for this scenario's traffic."""
        if self.target is not None:
            if self._target_stats is None:
                self._target_stats = self._stats_for(self.target)
            return self.target, self._target_stats
        if scenario.profile not in self._hosted:
            self.log(f"# hosting in-process replica "
                     f"(profile={scenario.profile})")
            self._hosted[scenario.profile] = _Hosted(
                scenario.profile, self.registry, self.recorder,
                self.incident_dir,
            )
        h = self._hosted[scenario.profile]
        self._warmup(h, scenario)
        return h.url, self._stats_for(h.url)

    def _warmup(self, hosted: _Hosted, scenario: Scenario) -> None:
        """Pay JIT compiles before the clock starts: one request at
        the scenario's longest prompt length (prefill shapes), one
        streaming, one constrained if the mix uses tools. Warmups
        are not counted anywhere."""
        model = WorkloadModel(self._workload_for(scenario))
        longest = max((len(s.tokens) for s in model.schedule()),
                      default=4)
        if longest <= hosted.warmed_len:
            return
        hosted.warmed_len = longest
        base = hosted.url + "/generate"
        _http_json(base, {"tokens": list(range(2, 2 + longest)),
                          "max_new": 2, "timeout": 120},
                   timeout=180.0)
        _http_json(base, {"tokens": [5, 6, 7], "max_new": 2,
                          "stream": True, "timeout": 120},
                   timeout=180.0)
        if "tool" in scenario.workload.mix:
            _http_json(base, {"tokens": [5, 6, 7], "max_new": 2,
                              "constraint": {
                                  "regex":
                                  scenario.workload.tool_regex},
                              "timeout": 120},
                       timeout=180.0)

    def _workload_for(self, scenario: Scenario) -> WorkloadConfig:
        wl = scenario.workload
        if self.seed is not None:
            from dataclasses import replace
            wl = replace(wl, seed=self.seed)
        if self.duration_scale != 1.0:
            wl = wl.scaled(self.duration_scale)
        return wl

    # ---- chaos ------------------------------------------------------

    def _with_chaos(self, scenario: Scenario, url: str,
                    duration_s: float):
        """Returns (traffic_url, arm_fn, teardown_fn). Control-plane
        calls (incident POST, trace resolution) keep the DIRECT url —
        chaos lives on the workload's wire only."""
        if scenario.chaos is None:
            return url, lambda: None, lambda: None
        if scenario.chaos == "unavailable_mid_run":
            parsed = urllib.parse.urlsplit(url)
            proxy = ChaosProxy(parsed.hostname, parsed.port)
            timers = [
                threading.Timer(duration_s / 3.0, proxy.unavailable),
                threading.Timer(2.0 * duration_s / 3.0,
                                proxy.pass_through),
            ]

            def arm():
                for t in timers:
                    t.daemon = True
                    t.start()

            def teardown():
                for t in timers:
                    t.cancel()
                proxy.close()

            return proxy.url, arm, teardown
        # kill_replica: a REAL serve subprocess, SIGKILLed mid-run.
        # The replica IS the scenario's target (run_scenario skips
        # self-hosting for this chaos kind). Warm with the schedule's
        # LONGEST payload so the compile for the real request shapes
        # is paid before the clock starts — a token-[1,2,3] warmup
        # leaves the first real batch stalled ~2.5s on compile, which
        # the kill timer then wrongly counts against availability.
        replica = ReplicaProc(model="tiny", slots=2, max_len=96)
        replica.wait_ready()
        wl = self._workload_for(scenario)
        longest = max(
            (p for _, p in WorkloadModel(wl).payload_schedule(
                timeout=120.0)),
            key=lambda p: len(p["tokens"]),
        )
        warm = {k: v for k, v in longest.items()
                if k not in ("tenant", "kind", "cancel_after_deltas",
                             "stream")}
        warm["timeout"] = 120
        _http_json(replica.url + "/generate", warm, timeout=180.0)
        # 3/4 in, not 1/2: the front of the window must land cleanly
        # so the verdict measures the death, not the ramp.
        timer = threading.Timer(0.75 * duration_s, replica.kill)

        def arm():
            timer.daemon = True
            timer.start()

        def teardown():
            timer.cancel()
            replica.kill()

        return replica.url, arm, teardown

    # ---- one scenario -----------------------------------------------

    def run_scenario(self, scenario: Scenario) -> Dict[str, object]:
        t0 = time.monotonic()
        wl = self._workload_for(scenario)
        model = WorkloadModel(wl)
        fingerprint = model.fingerprint()
        slo_strings = ((INDUCED_SLO,) if self.induce
                       and scenario.engine == "dense"
                       else scenario.slos)
        specs = parse_slo_specs(slo_strings)

        def row_base(verdict: str, skip: Optional[str],
                     slo_rows) -> Dict[str, object]:
            return {
                "schema": LEDGER_SCHEMA,
                "scenario": scenario.name,
                "description": scenario.description,
                "verdict": verdict,
                "skip_reason": skip,
                "engine": scenario.engine,
                "chaos": scenario.chaos,
                "requires": list(scenario.requires),
                "slos": slo_rows,
                "seed": wl.seed,
                "workload_fingerprint": fingerprint,
                "gate": scenario.gate,
            }

        self.recorder.record(
            None, "scenario-start", src="scenario",
            scenario=scenario.name, seed=wl.seed,
            requests=len(model.schedule()), chaos=scenario.chaos,
        )

        # Skips are decided BEFORE any target spins up: first the
        # static exclusion matrix, then the live target's /stats.
        skip = scenario.skip_reason()
        if skip is None and self.target is not None:
            if self._target_stats is None:
                self._target_stats = self._stats_for(self.target)
            skip = scenario.skip_reason(self._target_stats)
        if skip is not None:
            self.metrics.runs.labels(scenario=scenario.name,
                                     verdict="skip").inc()
            self.metrics.duration.observe(time.monotonic() - t0)
            self.recorder.record(
                None, "scenario-skip", src="scenario",
                scenario=scenario.name, reason=skip,
            )
            self.log(f"SKIP {scenario.name} ({skip})")
            return row_base("skip", skip, list(slo_strings))

        if scenario.chaos == "kill_replica" and self.target is None:
            # The chaos replica IS the target: no in-process host.
            url = None
        else:
            url, _stats = self._resolve_target(scenario)
        traffic_url, arm_chaos, teardown_chaos = self._with_chaos(
            scenario, url, wl.duration_s)
        if url is None:
            url = traffic_url
        try:
            gen = LoadGenerator(
                traffic_url,
                schedule=model.payload_schedule(timeout=self.timeout),
                timeout=self.timeout, capture=True,
                max_in_flight=self.max_in_flight,
            )
            arm_chaos()
            counts = gen.run()
        finally:
            teardown_chaos()

        for outcome, n in sorted(counts.items()):
            self.metrics.requests.labels(
                scenario=scenario.name, outcome=outcome).inc(n)

        slo_rows = evaluate_slos(specs, gen.results)
        violating = {r["slo"]: r["violating_trace"] for r in slo_rows}

        # Feed the cumulative counts through the real SLO engine:
        # gauges, burn rates, and — on a breach — a recorded
        # slo-transition carrying the violating-trace exemplar.
        engine = SLOEngine(
            specs, registry=self.registry, recorder=self.recorder,
            exemplar_fn=lambda spec: violating.get(spec.name),
        )
        base_now = time.monotonic()
        engine.tick({s.name: (0.0, 0.0) for s in specs}, now=base_now)
        engine.tick(
            {r["slo"]: (float(r["good"]), float(r["total"]))
             for r in slo_rows},
            now=base_now + max(wl.duration_s, 1.0),
        )

        verdict = "pass"
        for r in slo_rows:
            self.metrics.good_fraction.labels(
                scenario=scenario.name, slo=r["slo"]).set(
                r["good_fraction"] if r["good_fraction"] is not None
                else 0.0)
            if r["ok"]:
                continue
            verdict = "fail"
            self.metrics.breaches.labels(
                scenario=scenario.name, slo=r["slo"]).inc()
            tid = r["violating_trace"]
            incident, manifest_trace = self._fire_incident(
                url, scenario, r, tid)
            r["incident"] = incident
            r["incident_trace"] = manifest_trace
            r["trace_resolved"] = (self._trace_resolves(url, tid)
                                   if tid else False)
            self.recorder.record(
                tid, "scenario-slo-breach", src="scenario",
                scenario=scenario.name, slo=r["slo"],
                good_fraction=r["good_fraction"],
                objective=r["objective"], incident=incident,
            )

        self.metrics.runs.labels(scenario=scenario.name,
                                 verdict=verdict).inc()
        self.metrics.duration.observe(time.monotonic() - t0)
        self.recorder.record(
            None, "scenario-verdict", src="scenario",
            scenario=scenario.name, verdict=verdict,
            slos={r["slo"]: r["good_fraction"] for r in slo_rows},
        )
        row = row_base(verdict, None, slo_rows)
        row["counts"] = counts
        self.log(f"{verdict.upper():4s} {scenario.name} "
                 + " ".join(f"{r['slo']}={r['good_fraction']}"
                            for r in slo_rows))
        return row

    # ---- incidents --------------------------------------------------

    def _fire_incident(self, url: str, scenario: Scenario,
                       slo_row: Mapping, tid: Optional[str]
                       ) -> Tuple[Optional[str], Optional[str]]:
        """POST /debug/incident at the target so the PR 13 bundle
        machinery (rate limits, sections, retention) does the work;
        the x-shellac-trace header carries the violating trace id
        into the bundle manifest. Returns (bundle id, the manifest's
        trace id) — (None, None) when the target has no incident dir
        or the write was refused (reported, never raised)."""
        headers = {}
        if tid:
            headers[TRACE_HEADER] = format_trace_header(tid, 0)
        note = (f"scenario {scenario.name!r} SLO breach: "
                f"{slo_row['slo']} good_fraction="
                f"{slo_row['good_fraction']} < objective="
                f"{slo_row['objective']}")
        status, body = _http_json(
            url + "/debug/incident", {"note": note}, headers=headers,
            timeout=30.0,
        )
        if status != 200:
            self.log(f"# incident POST failed ({status}): "
                     f"{body.get('error', body)}")
            return None, None
        manifest = body.get("manifest") or {}
        return body.get("incident"), manifest.get("trace_id")

    def _trace_resolves(self, url: str, tid: str) -> bool:
        status, _ = _http_json(url + f"/debug/request/{tid}",
                               timeout=10.0)
        return status == 200

    # ---- many scenarios ---------------------------------------------

    def run(self, scenarios: Sequence[Scenario]
            ) -> List[Dict[str, object]]:
        rows = []
        for s in scenarios:
            rows.append(self.run_scenario(s))
        return rows


# ---------------------------------------------------------------------
# CLI entry (python -m shellac_tpu scenarios)


def select_scenarios(names: Optional[Sequence[str]],
                     include_all: bool) -> List[Scenario]:
    if names:
        unknown = [n for n in names if n not in SCENARIOS]
        if unknown:
            raise SystemExit(
                f"unknown scenario(s): {', '.join(unknown)} "
                f"(known: {', '.join(SCENARIOS)})"
            )
        return [SCENARIOS[n] for n in names]
    return [s for s in SCENARIOS.values() if s.gate or include_all]


def cli_run(args) -> int:
    if args.list:
        for s in SCENARIOS.values():
            skip = s.skip_reason()
            mark = ("skip: " + skip if skip
                    else ("gate" if s.gate else "full"))
            print(f"{s.name:24s} [{mark}] {s.description}")
        return 0

    selected = select_scenarios(args.scenario, args.all)

    if args.check:
        # No traffic: schema-check the committed ledger and diff it
        # against the statically-recomputable projection.
        try:
            doc = load_ledger(args.ledger)
            check_ledger(doc)
        except SchemaDrift as e:
            print(f"SCHEMA DRIFT: {e}")
            return 2
        gate_scns = [s for s in SCENARIOS.values() if s.gate]
        diff = compare_to_ledger(expected_static_rows(gate_scns),
                                 doc, verdict_known=False)
        if diff:
            print("STALE LEDGER (run `python -m shellac_tpu "
                  "scenarios --update-ledger`):")
            for line in diff:
                print(f"  {line}")
            return 3
        print(f"ledger {args.ledger} ok "
              f"({len(doc['scenarios'])} scenarios)")
        return 0

    runner = ScenarioRunner(
        target=args.target,
        incident_dir=args.incident_dir,
        timeout=args.timeout,
        duration_scale=args.duration_scale,
        seed=args.seed,
        induce_violation=args.induce_violation,
    )
    try:
        rows = runner.run(selected)
    finally:
        runner.close()

    for row in rows:
        check_row(row, committed=False)  # honor our own schema
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"schema": LEDGER_SCHEMA, "rows": rows}, f,
                      indent=1, sort_keys=True, default=str)
            f.write("\n")

    n_fail = sum(1 for r in rows if r["verdict"] == "fail")
    n_skip = sum(1 for r in rows if r["verdict"] == "skip")
    print(f"\n{len(rows)} scenarios: "
          f"{len(rows) - n_fail - n_skip} pass, {n_fail} fail, "
          f"{n_skip} skip")
    for r in rows:
        if r["verdict"] != "fail":
            continue
        for e in r["slos"]:
            if isinstance(e, Mapping) and not e.get("ok", True):
                print(f"  FAIL {r['scenario']} {e['slo']}: "
                      f"good_fraction={e['good_fraction']} "
                      f"incident={e.get('incident')} "
                      f"trace={e.get('violating_trace')}")

    if args.update_ledger:
        if args.scenario or args.seed is not None \
                or args.duration_scale != 1.0 or args.induce_violation:
            raise SystemExit(
                "--update-ledger must run the unmodified gate set "
                "(no --scenario/--seed/--duration-scale/"
                "--induce-violation)"
            )
        write_ledger(args.ledger, [r for r in rows if r["gate"]])
        print(f"wrote {args.ledger}")
        return 1 if n_fail else 0

    if args.gate and not args.induce_violation:
        try:
            doc = load_ledger(args.ledger)
            check_ledger(doc)
        except SchemaDrift as e:
            print(f"SCHEMA DRIFT: {e}")
            return 2
        gate_rows = [stable_row(r) for r in rows if r["gate"]]
        if not args.scenario:
            diff = compare_to_ledger(gate_rows, doc,
                                     verdict_known=True)
        else:
            # A filtered gate run compares only the selected rows.
            names = {r["scenario"] for r in gate_rows}
            sub = {"scenarios": [r for r in doc["scenarios"]
                                 if r["scenario"] in names]}
            diff = compare_to_ledger(gate_rows, sub,
                                     verdict_known=True)
        if diff:
            print("STALE LEDGER (run `python -m shellac_tpu "
                  "scenarios --update-ledger`):")
            for line in diff:
                print(f"  {line}")
            return 3 if not n_fail else 1

    return 1 if n_fail else 0
