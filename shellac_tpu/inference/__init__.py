from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.engine import Engine, GenerationResult, shard_params
from shellac_tpu.inference.kvcache import KVCache, cache_logical_axes, init_cache
from shellac_tpu.inference.server import InferenceServer
from shellac_tpu.inference.spec_batching import SpeculativeBatchingEngine
from shellac_tpu.inference.speculative import SpecResult, SpeculativeEngine

__all__ = [
    "BatchingEngine",
    "Engine",
    "InferenceServer",
    "PagedBatchingEngine",
    "GenerationResult",
    "KVCache",
    "init_cache",
    "cache_logical_axes",
    "SpecResult",
    "SpeculativeBatchingEngine",
    "SpeculativeEngine",
    "shard_params",
]
