from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.engine import Engine, GenerationResult, shard_params
from shellac_tpu.inference.kvcache import (
    KVCache,
    PatternedKVCache,
    QuantKVCache,
    QuantPagedKVCache,
    QuantPatternedKVCache,
    QuantRollingKVCache,
    RollingKVCache,
    cache_logical_axes,
    cache_logical_axes_for,
    init_cache,
    init_cache_for,
)
from shellac_tpu.inference.server import InferenceServer
from shellac_tpu.inference.spec_batching import SpeculativeBatchingEngine
from shellac_tpu.inference.speculative import SpecResult, SpeculativeEngine
from shellac_tpu.inference.tier import TierRouter

__all__ = [
    "TierRouter",
    "BatchingEngine",
    "Engine",
    "InferenceServer",
    "PagedBatchingEngine",
    "GenerationResult",
    "KVCache",
    "PatternedKVCache",
    "QuantKVCache",
    "QuantPagedKVCache",
    "QuantPatternedKVCache",
    "QuantRollingKVCache",
    "RollingKVCache",
    "init_cache",
    "init_cache_for",
    "cache_logical_axes",
    "cache_logical_axes_for",
    "SpecResult",
    "SpeculativeBatchingEngine",
    "SpeculativeEngine",
    "shard_params",
]
