from shellac_tpu.inference.engine import Engine, GenerationResult
from shellac_tpu.inference.kvcache import KVCache, cache_logical_axes, init_cache

__all__ = [
    "Engine",
    "GenerationResult",
    "KVCache",
    "init_cache",
    "cache_logical_axes",
]
